"""Native (C) host runtime pieces, loaded via ctypes.

The reference's host-side hot code is native Rust (the prio crate's XOF
expansion and codec, SURVEY.md section 2.2); this package holds the TPU
build's native equivalents. The shared library is compiled on first use
with the system compiler and cached next to the sources; everything has
a pure-Python fallback so the framework still works where no compiler
is available (`native.available()` reports which path is active).

Current contents:
  - xof.c — Keccak-f[1600]/SHAKE128 batch seed expansion with
    oversample-and-reduce field sampling (8*(limbs+1) stream bytes per
    element, reduced mod p) into u64 limb buffers (pthread-parallel
    across seeds), byte-compatible with janus_tpu.vdaf.xof.XofCtr128
    (counter-mode framing with tree-digested long binders).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "xof.c")
_LIB_NAME = f"libjanus_native-{sys.implementation.cache_tag}.so"

_lock = threading.Lock()
_lib = None
_tried = False


def _build(lib_path: str) -> bool:
    for cc in ("cc", "gcc", "clang", "g++"):
        try:
            # atomic publish: build to a temp name, rename into place
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            r = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC, "-lpthread"],
                capture_output=True,
                timeout=120,
            )
            if r.returncode == 0:
                os.replace(tmp, lib_path)
                return True
            os.unlink(tmp)
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _load():
    global _lib, _tried
    if _lib is not None:  # lock-free fast path once loaded
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib_path = os.path.join(_DIR, _LIB_NAME)
        try:
            if not os.path.exists(lib_path) or os.path.getmtime(
                lib_path
            ) < os.path.getmtime(_SRC):
                if not _build(lib_path):
                    return None
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        lib.janus_shake128.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_void_p,
            ctypes.c_size_t,
        ]
        lib.janus_expand_field_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_void_p,
            ctypes.c_int,
        ]
        lib.janus_expand_field_batch.restype = ctypes.c_int
        lib.janus_derive_seed_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_void_p,
        ]
        lib.janus_derive_seed_batch.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def shake128(data: bytes, outlen: int) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(outlen)
    lib.janus_shake128(data, len(data), out, outlen)
    return out.raw


def _n_threads(n: int, length: int) -> int:
    # one squeeze block ~ 21 permutations/KB; threading pays off quickly
    work = n * max(length, 1)
    if work < 2048:
        return 1
    return min(os.cpu_count() or 1, 16, n)


def expand_field_batch(
    dst16: bytes,
    seeds: np.ndarray | list[bytes],
    binders: np.ndarray | list[bytes] | None,
    length: int,
    limbs: int,
    modulus: int,
) -> np.ndarray | None:
    """Expand n seeds into an [n, length, limbs] u64 array, or None if the
    native library is unavailable. seeds: [n,16] u8 (or list of 16-byte
    strings); binders: [n, binder_len] u8 / list / None."""
    lib = _load()
    if lib is None:
        return None
    if not isinstance(seeds, np.ndarray):
        seeds = np.frombuffer(b"".join(seeds), dtype=np.uint8).reshape(-1, 16)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint8)
    n = seeds.shape[0]
    if binders is not None and not isinstance(binders, np.ndarray):
        joined = b"".join(binders)
        blen = len(joined) // n if n else 0
        binders = np.frombuffer(joined, dtype=np.uint8).reshape(n, blen)
    if binders is not None:
        binders = np.ascontiguousarray(binders, dtype=np.uint8)
        bptr = binders.ctypes.data_as(ctypes.c_void_p)
        blen = binders.shape[1]
    else:
        bptr, blen = None, 0
    out = np.empty((n, length, limbs), dtype=np.uint64)
    rc = lib.janus_expand_field_batch(
        dst16,
        seeds.ctypes.data_as(ctypes.c_void_p),
        n,
        bptr,
        blen,
        length,
        limbs,
        ctypes.c_uint64(modulus & 0xFFFFFFFFFFFFFFFF),
        ctypes.c_uint64(modulus >> 64),
        out.ctypes.data_as(ctypes.c_void_p),
        _n_threads(n, length),
    )
    if rc != 0:
        return None
    return out


def derive_seed_batch(
    dst16: bytes,
    seeds: np.ndarray | list[bytes],
    binders: np.ndarray | list[bytes] | None,
) -> np.ndarray | None:
    """out[i] = SHAKE128(dst16 || seed_i || binder_i)[:16] as [n,16] u8."""
    lib = _load()
    if lib is None:
        return None
    if not isinstance(seeds, np.ndarray):
        seeds = np.frombuffer(b"".join(seeds), dtype=np.uint8).reshape(-1, 16)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint8)
    n = seeds.shape[0]
    if binders is not None and not isinstance(binders, np.ndarray):
        joined = b"".join(binders)
        blen = len(joined) // n if n else 0
        binders = np.frombuffer(joined, dtype=np.uint8).reshape(n, blen)
    if binders is not None:
        binders = np.ascontiguousarray(binders, dtype=np.uint8)
        bptr = binders.ctypes.data_as(ctypes.c_void_p)
        blen = binders.shape[1]
    else:
        bptr, blen = None, 0
    out = np.empty((n, 16), dtype=np.uint8)
    rc = lib.janus_derive_seed_batch(
        dst16,
        seeds.ctypes.data_as(ctypes.c_void_p),
        n,
        bptr,
        blen,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        return None
    return out
