"""Sharded VDAF aggregation over a jax.sharding.Mesh.

The reference scales horizontally with DB-leased worker replicas and
rayon threads inside `prio` (SURVEY.md section 2.10). The TPU-native
equivalents built here:

  - **dp** (data parallel): the report batch axis. Reports are
    independent, so prepare/accumulate shards trivially; the final
    accumulate is a tree-reduce that XLA lowers to an all-reduce over
    ICI (the analog of the reference's batch_aggregation_shard_count
    write-sharding, accumulator.rs:92 — shards here are devices).
  - **sp** (vector parallel): the measurement-vector axis for large
    SumVec/Histogram tasks — the structural analog of sequence/context
    parallelism (SURVEY.md section 5 "Long-context"): out-share columns
    live sharded across devices and are only gathered at collection
    time.

No NCCL/MPI translation: shardings are declared with NamedSharding and
XLA inserts the collectives (scaling-book recipe: pick a mesh, annotate
shardings, let XLA do the rest).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..vdaf.registry import VdafInstance, prio3_batched


def make_mesh(dp: int, sp: int = 1, devices=None) -> Mesh:
    """A (dp, sp) device mesh; dp*sp must equal the device count used."""
    if devices is None:
        devices = jax.devices()
    n = dp * sp
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
    arr = np.array(devices[:n]).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def two_party_step(inst: VdafInstance, verify_key: bytes):
    """The full two-party device step over one report batch.

    Returns a pure function (jit it, or use jit_two_party_step to bind
    a mesh) mapping column-batched report arrays to both aggregate
    shares + the accepted-report count. This is the framework's
    "training step": everything the reference does per report in
    leader_initialized + helper_initialized + ping-pong finish +
    accumulate (aggregation_job_driver.rs:329-402,530-726;
    aggregator.rs:1775-1826), fused into one traced computation.
    """
    p3 = prio3_batched(inst)

    def step(nonce_lanes, public_parts, leader_meas, leader_proof, blind0, helper_seed, blind1):
        out0, seed0, ver0, part0 = p3.prepare_init_leader(
            verify_key, nonce_lanes, public_parts, leader_meas, leader_proof, blind0
        )
        out1, seed1, ver1, part1 = p3.prepare_init_helper(
            verify_key, nonce_lanes, public_parts, helper_seed, blind1
        )
        mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
        mask = p3.prepare_finish(seed0, prep_msg, mask)
        mask = p3.prepare_finish(seed1, prep_msg, mask)
        agg0 = p3.aggregate(out0, mask)
        agg1 = p3.aggregate(out1, mask)
        count = mask.sum()
        return agg0, agg1, count

    return step


def helper_init_step(inst: VdafInstance, verify_key: bytes):
    """Helper-side prepare_init only (the serving hot path,
    aggregator.rs:1775-1797): seeds in, verifier share + out share out."""
    p3 = prio3_batched(inst)

    def step(nonce_lanes, public_parts, helper_seed, blind1):
        out1, seed1, ver1, part1 = p3.prepare_init_helper(
            verify_key, nonce_lanes, public_parts, helper_seed, blind1
        )
        return out1, seed1, ver1, part1

    return step


def _field_spec(mesh, jf, batch_spec, tail_spec):
    return tuple(NamedSharding(mesh, P(batch_spec, tail_spec)) for _ in range(jf.LIMBS))


def jit_two_party_step(inst: VdafInstance, verify_key: bytes, mesh: Mesh):
    """jit the two-party step with report-batch sharding over 'dp' and
    vector sharding over 'sp'; aggregate shares come back replicated
    (XLA inserts the ICI all-reduce for the masked accumulate)."""
    p3 = prio3_batched(inst)
    jf = p3.jf
    dp = NamedSharding(mesh, P("dp"))
    dp2 = NamedSharding(mesh, P("dp", None))
    dp3 = NamedSharding(mesh, P("dp", None, None))
    meas_sh = _field_spec(mesh, jf, "dp", "sp")
    proof_sh = _field_spec(mesh, jf, "dp", None)
    rep_vec = tuple(NamedSharding(mesh, P("sp")) for _ in range(jf.LIMBS))
    rep = NamedSharding(mesh, P())

    in_shardings = (
        dp2,  # nonce lanes
        dp3 if p3.uses_joint_rand else None,  # public parts
        meas_sh,  # leader meas
        proof_sh,  # leader proof
        dp2 if p3.uses_joint_rand else None,  # blind0
        dp2,  # helper seed
        dp2 if p3.uses_joint_rand else None,  # blind1
    )
    out_shardings = (rep_vec, rep_vec, rep)
    return jax.jit(
        two_party_step(inst, verify_key),
        in_shardings=in_shardings,
        out_shardings=out_shardings,
    )
