"""Sharded VDAF aggregation over a jax.sharding.Mesh.

The reference scales horizontally with DB-leased worker replicas and
rayon threads inside `prio` (SURVEY.md section 2.10). The TPU-native
equivalents built here:

  - **dp** (data parallel): the report batch axis. Reports are
    independent, so prepare/accumulate shards trivially; the final
    accumulate is a tree-reduce that XLA lowers to an all-reduce over
    ICI (the analog of the reference's batch_aggregation_shard_count
    write-sharding, accumulator.rs:92 — shards here are devices).
  - **sp** (vector parallel): the measurement-vector axis for large
    SumVec/Histogram tasks — the structural analog of sequence/context
    parallelism (SURVEY.md section 5 "Long-context"): out-share columns
    live sharded across devices and are only gathered at collection
    time.

No NCCL/MPI translation: shardings are declared with NamedSharding and
XLA inserts the collectives (scaling-book recipe: pick a mesh, annotate
shardings, let XLA do the rest).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..vdaf.registry import VdafInstance, prio3_batched


def make_mesh(dp: int, sp: int = 1, devices=None) -> Mesh:
    """A (dp, sp) device mesh; dp*sp must equal the device count used."""
    if devices is None:
        devices = jax.devices()
    n = dp * sp
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
    arr = np.array(devices[:n]).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def choose_mesh_geometry(
    ndev: int,
    input_len: int,
    output_len: int,
    sp_min_input_len: int,
    max_dp: int,
    dp: int | None = None,
    sp: int | None = None,
) -> tuple[int, int]:
    """Pick the (dp, sp) serving geometry for a circuit on `ndev` devices.

    Auto (dp/sp None): dp = largest power of two <= ndev, capped at
    `max_dp` (every batch bucket must divide by dp); long-vector tasks
    (input_len >= sp_min_input_len, even input/output lengths) trade one
    dp factor for sp=2 so the measurement/out-share columns shard too.

    Explicit dp/sp (the `engine: mesh:` config stanza / JANUS_MESH_DP/SP
    overrides) are validated, not trusted: non-power-of-two dp rounds
    down (bucket divisibility), dp*sp is clamped to the devices that
    exist, and sp>1 on a circuit whose input/output lengths can't split
    evenly falls back to sp=1. One device — or an override forcing
    dp=sp=1 — means the single-device path: callers get (1, 1) and build
    no mesh.
    """
    if ndev <= 1:
        return 1, 1
    auto_dp = 1 << (ndev.bit_length() - 1)  # largest power of two <= ndev
    if sp is not None:
        sp = max(1, int(sp))
    if dp is not None:
        dp = max(1, int(dp))
        dp = 1 << (dp.bit_length() - 1)  # buckets must divide by dp
    vec_ok = (
        input_len >= sp_min_input_len and input_len % 2 == 0 and output_len % 2 == 0
    )
    if dp is None and sp is None:
        dp, sp = auto_dp, 1
        if dp >= 2 and vec_ok:
            sp = 2
            dp //= 2
    else:
        if sp is None:
            sp = 1
        if sp > 1 and not (input_len % sp == 0 and output_len % sp == 0):
            sp = 1
        if dp is None:
            dp = max(1, auto_dp // sp)
            dp = 1 << (dp.bit_length() - 1)
    while dp > 1 and dp * sp > ndev:
        dp //= 2
    if dp * sp > ndev:
        return 1, 1  # override asks for more devices than exist
    dp = min(dp, max_dp)
    return max(1, dp), max(1, sp)


def two_party_step(inst: VdafInstance, verify_key: bytes):
    """The full two-party device step over one report batch.

    Returns a pure function (jit it, or use jit_two_party_step to bind
    a mesh) mapping column-batched report arrays to both aggregate
    shares + the accepted-report count. This is the framework's
    "training step": everything the reference does per report in
    leader_initialized + helper_initialized + ping-pong finish +
    accumulate (aggregation_job_driver.rs:329-402,530-726;
    aggregator.rs:1775-1826), fused into one traced computation.
    """
    p3 = prio3_batched(inst)

    def step(nonce_lanes, public_parts, leader_meas, leader_proof, blind0, helper_seed, blind1):
        out0, seed0, ver0, part0 = p3.prepare_init_leader(
            verify_key, nonce_lanes, public_parts, leader_meas, leader_proof, blind0
        )
        out1, seed1, ver1, part1 = p3.prepare_init_helper(
            verify_key, nonce_lanes, public_parts, helper_seed, blind1
        )
        mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
        mask = p3.prepare_finish(seed0, prep_msg, mask)
        mask = p3.prepare_finish(seed1, prep_msg, mask)
        agg0 = p3.aggregate(out0, mask)
        agg1 = p3.aggregate(out1, mask)
        count = mask.sum()
        return agg0, agg1, count

    return step


def helper_init_step(inst: VdafInstance, verify_key: bytes):
    """Helper-side prepare_init only (the serving hot path,
    aggregator.rs:1775-1797): seeds in, verifier share + out share out."""
    p3 = prio3_batched(inst)

    def step(nonce_lanes, public_parts, helper_seed, blind1):
        out1, seed1, ver1, part1 = p3.prepare_init_helper(
            verify_key, nonce_lanes, public_parts, helper_seed, blind1
        )
        return out1, seed1, ver1, part1

    return step


def _field_spec(mesh, jf, batch_spec, tail_spec):
    return tuple(NamedSharding(mesh, P(batch_spec, tail_spec)) for _ in range(jf.LIMBS))


def jit_two_party_step(inst: VdafInstance, verify_key: bytes, mesh: Mesh):
    """jit the two-party step with report-batch sharding over 'dp' and
    vector sharding over 'sp'; aggregate shares come back replicated
    (XLA inserts the ICI all-reduce for the masked accumulate)."""
    p3 = prio3_batched(inst)
    jf = p3.jf
    dp = NamedSharding(mesh, P("dp"))
    dp2 = NamedSharding(mesh, P("dp", None))
    dp3 = NamedSharding(mesh, P("dp", None, None))
    meas_sh = _field_spec(mesh, jf, "dp", "sp")
    proof_sh = _field_spec(mesh, jf, "dp", None)
    rep_vec = tuple(NamedSharding(mesh, P("sp")) for _ in range(jf.LIMBS))
    rep = NamedSharding(mesh, P())

    in_shardings = (
        dp2,  # nonce lanes
        dp3 if p3.uses_joint_rand else None,  # public parts
        meas_sh,  # leader meas
        proof_sh,  # leader proof
        dp2 if p3.uses_joint_rand else None,  # blind0
        dp2,  # helper seed
        dp2 if p3.uses_joint_rand else None,  # blind1
    )
    out_shardings = (rep_vec, rep_vec, rep)
    return jax.jit(
        two_party_step(inst, verify_key),
        in_shardings=in_shardings,
        out_shardings=out_shardings,
    )
