"""Multi-chip parallelism: device meshes + sharded aggregation steps."""

from .api import helper_init_step, jit_two_party_step, make_mesh, two_party_step

__all__ = ["make_mesh", "two_party_step", "helper_init_step", "jit_two_party_step"]
