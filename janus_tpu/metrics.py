"""Process metrics: counters/histograms + Prometheus text exposition.

Equivalent of the reference's OpenTelemetry metrics layer
(aggregator/src/metrics.rs:53-80 install_metrics_exporter with a
Prometheus or OTLP exporter; counter definitions like
janus_aggregate_step_failure_counter at aggregator.rs:114-154). Here a
dependency-free registry renders the Prometheus text format, served by
the health/metrics listener in janus_tpu.binary_utils.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from bisect import bisect_left
from collections import defaultdict


def _escape_label_value(v: str) -> str:
    """Prometheus text exposition label-value escaping: backslash,
    double-quote and newline must be escaped or a single hostile value
    (a task id, an error string) corrupts the whole /metrics scrape."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


# ---------------------------------------------------------------------------
# Label matchers: the SLO engine (janus_tpu/slo.py) selects registry
# series by {label: matcher} where a matcher value is an exact string,
# a "~regex" (fullmatch), or a list of exact alternatives. Compiled
# once per SLO definition; absent labels never match.
# ---------------------------------------------------------------------------


def compile_matchers(matchers: dict | None) -> tuple:
    """{label: "v" | "~regex" | [alts]} -> immutable compiled form for
    labels_match (regexes pre-compiled)."""
    out = []
    for k, v in sorted((matchers or {}).items()):
        if isinstance(v, (list, tuple)):
            out.append((k, "in", frozenset(str(x) for x in v)))
        elif isinstance(v, str) and v.startswith("~"):
            out.append((k, "re", re.compile(v[1:])))
        else:
            out.append((k, "eq", str(v)))
    return tuple(out)


def labels_match(key: tuple[tuple[str, str], ...], compiled: tuple) -> bool:
    """True when every compiled matcher accepts the label set `key`
    (a metric-store key: sorted (name, value) tuples)."""
    if not compiled:
        return True
    d = dict(key)
    for name, kind, want in compiled:
        got = d.get(name)
        if got is None:
            return False
        got = str(got)
        if kind == "eq":
            if got != want:
                return False
        elif kind == "in":
            if got not in want:
                return False
        else:  # "re"
            if not want.fullmatch(got):
                return False
    return True


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple[tuple[str, str], ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def add(self, n: float = 1, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += n

    def get(self, **labels) -> float:
        # the lock, not the GIL, is the documented guarantee: a reader
        # must never observe a torn/partial update even if the value
        # type grows beyond a float
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0)

    def total(self) -> float:
        """Sum across all label sets (shed accounting in bench/tests)."""
        with self._lock:
            return sum(self._values.values())

    def sum_matching(self, compiled: tuple) -> tuple[float, int]:
        """(sum, matched series count) over label sets accepted by the
        compiled matchers (compile_matchers). The count lets a caller
        distinguish "0 because idle" from "0 because the series does
        not exist yet" — the SLO engine treats the latter as no-data."""
        total = 0.0
        n = 0
        with self._lock:
            for key, v in self._values.items():
                if labels_match(key, compiled):
                    total += v
                    n += 1
        return total, n

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        for labels, v in items:
            lines.append(f"{self.name}{_fmt_labels(labels)} {v}")
        return "\n".join(lines)


class Gauge:
    """Instantaneous value (queue depths, in-flight counts)."""

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple[tuple[str, str], ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def set(self, v: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = v

    def add(self, n: float = 1, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += n

    def get(self, **labels) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0)

    def total(self) -> float:
        """Sum across all label sets (mirrors Counter.total)."""
        with self._lock:
            return sum(self._values.values())

    def sum_matching(self, compiled: tuple) -> tuple[float, int]:
        """(sum, matched series count) — see Counter.sum_matching."""
        total = 0.0
        n = 0
        with self._lock:
            for key, v in self._values.items():
                if labels_match(key, compiled):
                    total += v
                    n += 1
        return total, n

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        for labels, v in items:
            lines.append(f"{self.name}{_fmt_labels(labels)} {v}")
        return "\n".join(lines)


# The reference's custom boundaries for DB/HTTP latencies (metrics.rs)
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0,
)


def _exemplar_trace_hex(raw) -> str:
    """Hex form of a stored exemplar trace id (raw int for locally
    generated spans, hex str when adopted from a traceparent)."""
    return raw if isinstance(raw, str) else f"{raw:032x}"


class Histogram:
    # Bound on the (label set, bucket) exemplar store per histogram:
    # exemplars are a debugging aid (a firing latency alert links to a
    # concrete /debug/traces capture), never an unbounded cardinality
    # vector. Past the cap, NEW label sets stop collecting exemplars;
    # existing ones keep last-write semantics.
    MAX_EXEMPLAR_LABEL_SETS = 64

    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts: dict[tuple[tuple[str, str], ...], list[int]] = {}
        self._sums: dict[tuple[tuple[str, str], ...], float] = defaultdict(float)
        self._totals: dict[tuple[tuple[str, str], ...], int] = defaultdict(int)
        # {label key: {bucket idx: (trace_id raw, value, unix_ts)}};
        # bucket idx == len(buckets) is the +Inf bucket. Last write
        # wins — the freshest trace for "what blew this bucket".
        self._exemplars: dict[tuple, dict[int, tuple]] = {}

    def observe(self, value: float, exemplar_trace_id=None, **labels) -> None:
        """Record `value`. An exemplar trace id is attached to the
        observed bucket when given explicitly (the span->metric bridge
        passes the exiting span's trace id) or when an ambient trace
        context is live on this thread (trace.current_context) — so a
        latency histogram sample can be resolved to a concrete
        /debug/traces capture. Rendered only in OpenMetrics mode; the
        default exposition stays bit-compatible."""
        key = tuple(sorted(labels.items()))
        # first bucket with bound >= value; == len(buckets) -> only +Inf
        idx = bisect_left(self.buckets, value)
        if exemplar_trace_id is None:
            ctx = _trace_context()
            if ctx is not None:
                exemplar_trace_id = ctx[0]
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            if idx < len(self.buckets):
                counts[idx] += 1
            self._sums[key] += value
            self._totals[key] += 1
            if exemplar_trace_id is not None:
                slot = self._exemplars.get(key)
                if slot is None:
                    if len(self._exemplars) >= self.MAX_EXEMPLAR_LABEL_SETS:
                        return
                    slot = self._exemplars[key] = {}
                slot[idx] = (exemplar_trace_id, value, time.time())

    def le_total_matching(self, le: float, compiled: tuple) -> tuple[float, float, int]:
        """(observations <= bucket bound `le`, total observations,
        matched series count) summed over the label sets accepted by
        `compiled` (compile_matchers). `le` must be one of this
        histogram's bucket bounds (use nearest_bucket_le); the SLO
        engine's latency signals read good/total from here."""
        idx = bisect_left(self.buckets, le)
        good = 0.0
        total = 0.0
        n = 0
        with self._lock:
            for key, counts in self._counts.items():
                if labels_match(key, compiled):
                    good += sum(counts[: idx + 1])
                    total += self._totals[key]
                    n += 1
        return good, total, n

    def nearest_bucket_le(self, threshold_s: float) -> float:
        """Smallest bucket bound >= threshold_s (the effective latency
        threshold — an SLO threshold between bounds rounds UP so "under
        threshold" never overcounts good events). Falls back to the
        largest finite bound when the threshold exceeds every bucket."""
        idx = bisect_left(self.buckets, threshold_s)
        return self.buckets[min(idx, len(self.buckets) - 1)]

    def exemplars(self) -> list[dict]:
        """Snapshot of the stored exemplars (debug bundle / tests):
        [{labels, le, trace_id, value, ts}]."""
        out = []
        with self._lock:
            items = [
                (key, dict(slots)) for key, slots in sorted(self._exemplars.items())
            ]
        for key, slots in items:
            for idx, (tid, value, ts) in sorted(slots.items()):
                le = f"{self.buckets[idx]:g}" if idx < len(self.buckets) else "+Inf"
                out.append(
                    {
                        "labels": _labels_dict(key),
                        "le": le,
                        "trace_id": _exemplar_trace_hex(tid),
                        "value": value,
                        "ts": ts,
                    }
                )
        return out

    def _exemplar_suffix(self, key: tuple, idx: int) -> str:
        """OpenMetrics exemplar clause for bucket `idx` of label set
        `key` (lock held), or ''."""
        slot = self._exemplars.get(key)
        if not slot or idx not in slot:
            return ""
        tid, value, ts = slot[idx]
        return f' # {{trace_id="{_exemplar_trace_hex(tid)}"}} {value:g} {ts:.3f}'

    def render(self, openmetrics: bool = False) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = sorted(self._counts)
            for key in keys:
                cum = 0
                for i, (b, c) in enumerate(zip(self.buckets, self._counts[key])):
                    cum += c
                    lbl = _fmt_labels(key + (("le", f"{b:g}"),))
                    ex = self._exemplar_suffix(key, i) if openmetrics else ""
                    lines.append(f"{self.name}_bucket{lbl} {cum}{ex}")
                ex = (
                    self._exemplar_suffix(key, len(self.buckets))
                    if openmetrics
                    else ""
                )
                lines.append(
                    f'{self.name}_bucket{_fmt_labels(key + (("le", "+Inf"),))} {self._totals[key]}{ex}'
                )
                lines.append(f"{self.name}_sum{_fmt_labels(key)} {self._sums[key]}")
                lines.append(f"{self.name}_count{_fmt_labels(key)} {self._totals[key]}")
        return "\n".join(lines)


def _trace_context():
    """Lazy indirection to trace.current_context (importing trace at
    module level here would cycle: trace's import tail feeds the
    span->metric bridge registrations from this module)."""
    global _trace_context
    from .trace import current_context

    _trace_context = current_context
    return current_context()


def _labels_dict(key: tuple[tuple[str, str], ...]) -> dict:
    return {k: str(v) for k, v in key}


def task_id_label(task_id_bytes: bytes) -> str:
    """Canonical task-id label value (unpadded urlsafe base64, the DAP
    URL form). One definition — the per-task series (reports
    aggregated, aggregation lag) must agree on the encoding or one
    task's metrics silently split across two label values."""
    import base64

    return base64.urlsafe_b64encode(task_id_bytes).rstrip(b"=").decode()


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            assert isinstance(m, Counter)
            return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help_)
                self._metrics[name] = m
            assert isinstance(m, Gauge)
            return m

    def histogram(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets)
                self._metrics[name] = m
            assert isinstance(m, Histogram)
            return m

    def metrics_list(self) -> list:
        """Stable copy of the registered metric objects, taken under the
        registry lock (exporters iterating `_metrics` directly race a
        concurrent counter()/histogram() registration)."""
        with self._lock:
            return list(self._metrics.values())

    def get(self, name: str):
        """The registered metric object named `name`, or None (the SLO
        engine resolves YAML-named series lazily per tick)."""
        with self._lock:
            return self._metrics.get(name)

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition. With openmetrics=True, histogram
        buckets additionally carry their stored exemplars in OpenMetrics
        exemplar syntax and the output ends with `# EOF`; the default
        mode's bytes are unaffected by any stored exemplar."""
        parts = [
            m.render(openmetrics) if isinstance(m, Histogram) else m.render()
            for m in self.metrics_list()
        ]
        if openmetrics:
            parts.append("# EOF")
        return "\n".join(parts) + "\n"

    def snapshot(self) -> dict:
        """JSON-shaped dump of every metric (the /debug/vars payload and
        the bench rider's metric snapshot)."""
        out: dict = {}
        for m in self.metrics_list():
            if isinstance(m, Histogram):
                with m._lock:
                    samples = [
                        {
                            "labels": _labels_dict(key),
                            "sum": m._sums[key],
                            "count": m._totals[key],
                            "buckets": dict(
                                zip((f"{b:g}" for b in m.buckets), m._counts[key])
                            ),
                        }
                        for key in sorted(m._counts)
                    ]
                out[m.name] = {"type": "histogram", "help": m.help, "samples": samples}
            else:
                kind = "counter" if isinstance(m, Counter) else "gauge"
                with m._lock:
                    samples = [
                        {"labels": _labels_dict(key), "value": v}
                        for key, v in sorted(m._values.items())
                    ]
                out[m.name] = {"type": kind, "help": m.help, "samples": samples}
        return out


REGISTRY = MetricsRegistry()

# Counters mirroring the reference's (aggregator.rs:114-245)
upload_decrypt_failure_counter = REGISTRY.counter(
    "janus_upload_decrypt_failures", "reports which failed HPKE decryption at upload"
)
upload_replay_counter = REGISTRY.counter(
    "janus_upload_replayed_reports", "Duplicate report uploads ignored"
)
upload_decode_failure_counter = REGISTRY.counter(
    "janus_upload_decode_failures", "reports which failed decoding at upload"
)
aggregate_step_failure_counter = REGISTRY.counter(
    "janus_aggregate_step_failures",
    "per-report failures during aggregation steps, by type",
)
job_cancel_counter = REGISTRY.counter(
    "janus_job_cancellations", "jobs abandoned after repeated failures"
)
engine_oom_retry_counter = REGISTRY.counter(
    "janus_engine_oom_retries",
    "device OOMs absorbed by halving the engine's batch bucket cap",
)
engine_host_fallback_counter = REGISTRY.counter(
    "janus_engine_host_fallbacks",
    "engines that hit the bucket floor on device OOM and fell back to the host engine",
)
http_request_counter = REGISTRY.counter(
    "janus_http_requests", "DAP HTTP requests by route and status"
)
http_request_duration = REGISTRY.histogram(
    "janus_http_request_duration_seconds", "DAP HTTP request latency"
)
tx_duration = REGISTRY.histogram(
    "janus_database_transaction_duration_seconds", "datastore transaction latency"
)
tx_retries_total = REGISTRY.counter(
    "janus_tx_retries_total",
    "datastore transaction attempts that failed retryably, by tx name and "
    'error class (kind="serialization" is contention, kind="connection" is '
    "an outage — alert on the latter)",
)
# --- datastore connection supervision (datastore/store.py
# DatastoreSupervisor; docs/ROBUSTNESS.md "Datastore outages") ---
datastore_up = REGISTRY.gauge(
    "janus_datastore_up",
    "1 while the datastore health probe reports the database reachable "
    "(state up/degraded/recovering), 0 while down",
)
datastore_consecutive_failures = REGISTRY.gauge(
    "janus_datastore_consecutive_failures",
    "consecutive connection-class datastore failures observed by the "
    "supervisor (probe + real transactions); resets on success",
)
# --- durable upload spill journal (janus_tpu/ingest/journal.py) ---
upload_journal_depth = REGISTRY.gauge(
    "janus_upload_journal_depth",
    "reports sitting in the on-disk upload spill journal awaiting replay "
    "(0 in steady state; alert on sustained growth)",
)
upload_journal_bytes = REGISTRY.gauge(
    "janus_upload_journal_bytes", "on-disk bytes held by the upload spill journal"
)
upload_journal_appends_total = REGISTRY.counter(
    "janus_upload_journal_appends_total",
    "reports spilled to the upload journal instead of the datastore "
    "(each was acked 201 on the strength of the journal fsync)",
)
upload_journal_replayed_total = REGISTRY.counter(
    "janus_upload_journal_replayed_total",
    "journaled reports replayed into the datastore, by outcome "
    '(outcome="fresh" newly written, outcome="replayed" deduplicated)',
)
# --- ingest pipeline (janus_tpu.ingest; docs/INGEST.md) ---
upload_shed_counter = REGISTRY.counter(
    "janus_upload_shed_total",
    "requests rejected 429 by the admission controller, by route and reason",
)
ingest_queue_depth = REGISTRY.gauge(
    "janus_ingest_queue_depth", "ingest pipeline stage queue depths, by stage"
)
ingest_inflight = REGISTRY.gauge(
    "janus_ingest_inflight", "uploads admitted and not yet committed/failed"
)
ingest_stage_duration = REGISTRY.histogram(
    "janus_ingest_stage_duration_seconds",
    "per-report ingest stage latency (decode, decrypt, commit), by stage "
    "(batched windows observe the window's amortized per-report share)",
)
# --- batched ingest crypto/decode (ISSUE 11; docs/INGEST.md "Batched
# decrypt"): window sizes actually achieved by the flush-window
# batching, and the wall time of one batched decrypt+validate pass ---
hpke_batch_size = REGISTRY.histogram(
    "janus_hpke_batch_size",
    "reports per batched HPKE-open call (upload decrypt stage and the "
    "helper's aggregate-init stage; 1 = the batching never found a "
    "window — watch with the linger knob)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
ingest_decrypt_batch_seconds = REGISTRY.histogram(
    "janus_ingest_decrypt_batch_seconds",
    "wall time of one window-batched decrypt+validate pass on the "
    "ingest pipeline (whole window, not per report)",
)

# --- device path: engine/dispatch metrics (docs/OBSERVABILITY.md
# "Engine metrics"; ISSUE 3). The *_seconds histograms are fed by the
# span->metric bridge (trace.register_span_metric, registrations at the
# bottom of this module) so the Chrome-trace spans and the Prometheus
# series measure the same boundaries by construction. ---
engine_dispatch_seconds = REGISTRY.histogram(
    "janus_engine_dispatch_seconds",
    "device engine step wall time split into put/dispatch/fetch, by op and VDAF",
)
# first compiles run seconds-to-minutes (remote AOT through the tunnel):
# the default DB/HTTP buckets top out at 30s and would flatten them
COMPILE_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1200.0)
engine_compile_seconds = REGISTRY.histogram(
    "janus_engine_compile_seconds",
    "first-call (trace+compile) latency per (op, batch bucket)",
    buckets=COMPILE_BUCKETS,
)
engine_dispatches_total = REGISTRY.counter(
    "janus_engine_dispatches_total", "device engine dispatches, by op"
)
engine_rows_total = REGISTRY.counter(
    "janus_engine_rows_total", "report rows through the device engine, by op"
)
engine_bucket_cap = REGISTRY.gauge(
    "janus_engine_bucket_cap",
    "current HBM-feasibility batch bucket cap per VDAF kind (0 = uncapped)",
)
engine_batch_fill_ratio = REGISTRY.gauge(
    "janus_engine_batch_fill_ratio",
    "rows / padded bucket of the most recent dispatch, by op (padding waste)",
)
engine_cache_entries = REGISTRY.gauge(
    "janus_engine_cache_entries", "live compiled-engine cache entries"
)
engine_cache_hits = REGISTRY.counter(
    "janus_engine_cache_hits_total", "engine cache lookups served from cache"
)
engine_cache_misses = REGISTRY.counter(
    "janus_engine_cache_misses_total", "engine cache lookups that built a new engine"
)
engine_coalesced_rounds_total = REGISTRY.counter(
    "janus_engine_coalesced_rounds_total",
    "device dispatch rounds that merged more than one concurrent caller",
)
engine_coalesced_rows_total = REGISTRY.counter(
    "janus_engine_coalesced_rows_total",
    "report rows carried by coalesced (multi-caller) dispatch rounds",
)
engine_backend_state = REGISTRY.gauge(
    "janus_engine_backend",
    "1 for the active engine backend per VDAF kind "
    '(state="device|host_fallback|timed_fallback|quarantined|host"), 0 otherwise',
)

# --- device-path watchdog + quarantine (aggregator/device_watchdog.py,
# engine_cache quarantine/canary; docs/ROBUSTNESS.md "Device hangs &
# deadlines") ---
hung_dispatches_total = REGISTRY.counter(
    "janus_hung_dispatches_total",
    "device dispatches abandoned by the watchdog after exceeding the "
    "caller's deadline (lease budget / propagated request deadline), by "
    "VDAF and op — alert on any nonzero rate",
)
abandoned_dispatch_threads = REGISTRY.gauge(
    "janus_abandoned_dispatch_threads",
    "watchdog worker threads currently parked on a hung device dispatch; "
    "reaching the configured cap trips host-only mode",
)
engine_quarantines_total = REGISTRY.counter(
    "janus_engine_quarantines_total",
    "device-circuit quarantine events per VDAF kind, by event "
    '(event="open|canary_probe|canary_failed|restored")',
)
request_deadline_exceeded_total = REGISTRY.counter(
    "janus_request_deadline_exceeded_total",
    "units of work dropped mid-stage because their propagated deadline "
    "(DAP-Janus-Deadline / lease budget) expired, by stage",
)

# --- job/task health (aggregator/health_sampler.py; sampled except the
# accumulate-time counter) ---
jobs_gauge = REGISTRY.gauge(
    "janus_jobs", "datastore job backlog, by job type and state (sampled)"
)
job_lease_age_seconds = REGISTRY.gauge(
    "janus_job_lease_age_seconds",
    "max age of any outstanding job lease since the sampler first observed it",
)
oldest_unaggregated_report_age_seconds = REGISTRY.gauge(
    "janus_oldest_unaggregated_report_age_seconds",
    "age of the oldest report not yet claimed by an aggregation job, per task "
    "(the aggregation-lag SLO signal)",
)
task_reports_aggregated_total = REGISTRY.counter(
    "janus_task_reports_aggregated_total",
    "reports merged into batch aggregations, per task (counted at accumulate time)",
)
batches_pending_collection = REGISTRY.gauge(
    "janus_batches_pending_collection",
    "collection jobs awaiting an aggregate result (sampled)",
)

# --- robustness: fault injection + outbound circuit breaker
# (janus_tpu/failpoints.py, core/circuit_breaker.py; docs/ROBUSTNESS.md) ---
failpoints_fired_total = REGISTRY.counter(
    "janus_failpoints_fired_total",
    "injected faults fired, by failpoint name and action (zero in production)",
)
outbound_circuit_state = REGISTRY.gauge(
    "janus_outbound_circuit_state",
    "leader->peer outbound circuit breaker state per peer "
    "(0=closed, 1=open, 2=half-open)",
)
outbound_circuit_transitions = REGISTRY.counter(
    "janus_outbound_circuit_transitions_total",
    "circuit breaker state transitions, by peer and destination state",
)
job_step_back_total = REGISTRY.counter(
    "janus_job_step_back_total",
    "job steps that released their lease early (breaker open, shutdown drain) "
    "instead of failing, by reason",
)

# --- peer-outage parking + half-open probing (aggregator/peer_health.py;
# docs/ARCHITECTURE.md "Surviving the other aggregator") ---
peer_parked = REGISTRY.gauge(
    "janus_peer_parked",
    "1 while job claims targeting this peer are parked (the peer's outbound "
    "circuit is open and the cheap half-open probe has not yet seen it alive)",
)
peer_outage_seconds_total = REGISTRY.counter(
    "janus_peer_outage_seconds_total",
    "cumulative seconds each peer's outbound circuit spent not-closed "
    "(open or half-open), accumulated by the peer-health prober tick",
)
peer_probes_total = REGISTRY.counter(
    "janus_peer_probes_total",
    "cheap half-open peer probes issued by the peer-health prober, by peer "
    'and outcome (outcome="alive|dead|rejected"; rejected = another probe '
    "held the single half-open slot)",
)

# --- stage-pipelined leader stepper (aggregator/step_pipeline.py;
# docs/ARCHITECTURE.md "The stepper pipeline", ISSUE 9) ---
step_pipeline_stage_seconds = REGISTRY.histogram(
    "janus_step_pipeline_stage_seconds",
    "per-stage execution wall time of the pipelined leader stepper, by "
    'stage (stage="read|device|http|commit|classic"; queue wait excluded)',
)
step_pipeline_queue_depth = REGISTRY.gauge(
    "janus_step_pipeline_queue_depth",
    "jobs handed to a pipeline stage and not yet executing, by stage",
)
device_lane_busy_ratio = REGISTRY.gauge(
    "janus_device_lane_busy_ratio",
    "fraction of wall time the pipeline's serialized device lane spent "
    "executing device stages over a rolling ~60-120s window (the "
    "chip-saturation signal; sustained ~1.0 = device-bound — compare "
    "with stage seconds to find the bottleneck stage)",
)
device_lane_busy_seconds = REGISTRY.counter(
    "janus_device_lane_busy_seconds_total",
    "cumulative seconds the device lane spent executing device stages — "
    "rate() this for alerting windows of any width (the gauge above is "
    "a fixed rolling window)",
)
step_pipeline_overlap_total = REGISTRY.counter(
    "janus_step_pipeline_overlap_total",
    "pipeline overlap events, by direction: a device-lane stage started "
    'while a helper HTTP leg was in flight (direction="device_start") or '
    'an HTTP leg started while the lane was busy (direction="http_start") '
    "— either nonzero proves the pipeline is hiding the helper RTT "
    "behind device work",
)
prep_resp_order_mismatch_total = REGISTRY.counter(
    "janus_prep_resp_order_mismatch_total",
    "helper responses whose prepare_resps came back out of request order "
    "(a DAP ordering-contract violation; the driver falls back to the "
    "id->index dict match)",
)

# --- single-controller mesh dispatch queue (aggregator/engine_cache.py
# MeshDispatchQueue; docs/ARCHITECTURE.md "Multi-chip serving") ---
mesh_dispatch_total = REGISTRY.counter(
    "janus_mesh_dispatch_total",
    "mesh programs dispatched through the single-controller queue, by "
    "program (the jit variant name) — every multi-device enqueue in the "
    "process rides this lane",
)
mesh_dispatch_queue_depth = REGISTRY.gauge(
    "janus_mesh_dispatch_queue_depth",
    "mesh dispatches submitted to the single-controller lane and not yet "
    "executing (sustained >0 = the dispatch lane, not the devices, is "
    "the ceiling — compare with wait_seconds)",
)
mesh_dispatch_wait_seconds = REGISTRY.histogram(
    "janus_mesh_dispatch_wait_seconds",
    "time a mesh dispatch spent queued behind other programs before the "
    "lane thread picked it up (the cross-engine serialization cost the "
    "old process-global lock hid inside dispatch wall time)",
)
mesh_dispatch_busy_seconds = REGISTRY.counter(
    "janus_mesh_dispatch_busy_seconds_total",
    "cumulative seconds the mesh dispatch lane spent enqueueing programs "
    "(execution stays async on the devices; rate() vs wall clock gives "
    "the lane's own saturation)",
)

# --- device-resident aggregate state + host<->device traffic (ISSUE 12;
# docs/ARCHITECTURE.md "Resident aggregate state") ---
engine_resident_buffers = REGISTRY.gauge(
    "janus_engine_resident_buffers",
    "per-(task, batch bucket) aggregate buffers currently resident in "
    "device memory, by VDAF kind (flushed to the datastore on interval, "
    "LRU pressure, quarantine and drain)",
)
engine_resident_bytes = REGISTRY.gauge(
    "janus_engine_resident_bytes",
    "device bytes held by resident aggregate buffers across all engines "
    "(bounded by the engine resident_max_bytes knob; overflow evicts LRU "
    "buffers through the flush path)",
)
engine_hd_bytes_total = REGISTRY.counter(
    "janus_engine_hd_bytes_total",
    "host<->device bytes moved by the engine layer, by direction "
    '(direction="h2d" staging uploads + masks, direction="d2h" fetches) '
    "— the resident-accumulator A/B divides this by rows to get "
    "bytes/report on the accumulate leg",
)
engine_resident_flushes_total = REGISTRY.counter(
    "janus_engine_resident_flushes_total",
    "resident aggregate buffers flushed through the write-tx path, by "
    'reason (reason="interval|eviction|quarantine|drain|merge_failed") '
    'and outcome (outcome="flushed|lost|stale") — outcome="lost" means a '
    "fetched share could not be persisted and is gone; alert on any",
)
engine_scatter_rows_total = REGISTRY.counter(
    "janus_engine_scatter_rows_total",
    "verified sparse reports scatter-added into a dense logical "
    "accumulator (resident scatter-merge or the classic sparse "
    "aggregate), by VDAF kind — the block-sparse analogue of "
    "aggregated rows; zero on a sparse task means the scatter path "
    "never ran",
)
engine_sparse_block_occupancy = REGISTRY.gauge(
    "janus_engine_sparse_block_occupancy",
    "mean fraction of a sparse report's max_blocks block slots that "
    "carried a real (non-padding) block in the most recent scatter "
    "dispatch, by VDAF kind — near 1.0 means clients saturate the "
    "compact encoding and the task geometry should grow max_blocks",
)
engine_prestage_total = REGISTRY.counter(
    "janus_engine_prestage_total",
    "double-buffered staging outcomes: a prestaged (async H2D during the "
    'previous dispatch) column set consumed by its dispatch (outcome="hit") '
    'or discarded for the host re-stage path (outcome="fallback" — '
    "coalesced multi-job round, bucket cap moved, or host fallback)",
)

# --- report-lifecycle tracing + end-to-end SLOs (ISSUE 6;
# docs/OBSERVABILITY.md "Report-lifecycle tracing") ---
span_errors_total = REGISTRY.counter(
    "janus_span_errors_total",
    "spans that exited with an exception (error=<ExcType> on the emitted "
    "event), by span name",
)
otlp_spans_dropped_total = REGISTRY.counter(
    "janus_otlp_spans_dropped_total",
    "spans dropped oldest-first from the OTLP export buffer while the "
    "collector was unreachable",
)
# DAP end-to-end latency runs seconds-to-hours (upload -> aggregate ->
# collectable batch); the default DB/HTTP buckets top out at 30s
E2E_BUCKETS = (
    0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 1800.0, 3600.0, 7200.0,
    21600.0, 86400.0,
)
report_e2e_seconds = REGISTRY.histogram(
    "janus_report_e2e_seconds",
    "end-to-end DAP latency by stage: client report timestamp to verified "
    'output share (stage="aggregate", observed at accumulate time) and batch '
    'close to aggregate share released (stage="collect")',
    buckets=E2E_BUCKETS,
)
unaggregated_report_age_quantiles = REGISTRY.gauge(
    "janus_unaggregated_report_age_seconds",
    "per-task age quantiles (p50/p95/p99) of reports not yet claimed by an "
    "aggregation job (sampled; the freshness distribution behind the "
    "oldest-report gauge)",
)

# --- in-process SLO burn-rate engine (janus_tpu/slo.py; ISSUE 10,
# docs/OBSERVABILITY.md "SLO engine & /alertz") ---
alert_active = REGISTRY.gauge(
    "janus_alert_active",
    "1 while the named burn-rate alert is firing, 0 otherwise "
    "(evaluated in-process by the SLO engine; the full state — burn "
    "rates, budget, firing-since, evidence — is GET /alertz)",
)
slo_error_budget_remaining = REGISTRY.gauge(
    "janus_slo_error_budget_remaining_ratio",
    "fraction of the SLO's error budget left over its budget window "
    "(1 = untouched, 0 = exhausted, negative = overspent)",
)
slo_burn_rate = REGISTRY.gauge(
    "janus_slo_burn_rate",
    "error-budget burn rate per SLO and evaluation window (1.0 = "
    "spending exactly the budget; the SRE-workbook ladder pages at "
    "14.4x over 1h and tickets at 6x over 6h)",
)

# --- always-on continuous profiler + device cost ledger + boot
# timeline (janus_tpu/profiler.py; ISSUE 13, docs/OBSERVABILITY.md
# "Continuous profiling") ---
profiler_samples_total = REGISTRY.counter(
    "janus_profiler_samples_total",
    "sampling passes completed by the wall-clock stack profiler "
    "(each pass folds every live thread's stack into /debug/profile)",
)
profiler_threads = REGISTRY.gauge(
    "janus_profiler_threads",
    "threads captured by the profiler's most recent sampling pass",
)
profiler_overhead_ratio = REGISTRY.gauge(
    "janus_profiler_overhead_ratio",
    "measured fraction of wall time the sampling profiler spends in its "
    "own passes over the retained windows (0 while off; alert well "
    "before the 2% budget)",
)
device_cost_seconds_total = REGISTRY.counter(
    "janus_device_cost_seconds_total",
    "cumulative device-path wall time attributed by the per-dispatch "
    'cost ledger, by op and phase (phase="compile|execute|h2d|d2h"; '
    "per-(vdaf, op, bucket) detail is the /statusz device_cost section)",
)
device_cost_us_per_report = REGISTRY.gauge(
    "janus_device_cost_us_per_report",
    "live microseconds of device-path wall time per report row, by op "
    "and phase (an op's cumulative phase seconds over its cumulative "
    "rows — what the device-lane busy time BUYS per report)",
)
engine_prewarm_total = REGISTRY.counter(
    "janus_engine_prewarm_total",
    "manifest-driven engine prewarm outcomes per specialization "
    '(outcome="warmed" compiled/loaded before use, "deferred" pushed '
    "past the boot budget to the background warmer (each later also "
    'counts warmed/failed), "skipped_covered" legacy warmup skipped a '
    'geometry the manifest prewarm owns, "unsupported" a recorded '
    'variant the warmer cannot synthesize, "no_task" no provisioned '
    'task matches the recorded vdaf, "failed")',
)
engine_prewarm_seconds = REGISTRY.histogram(
    "janus_engine_prewarm_seconds",
    "wall seconds to warm one recorded specialization at boot (a "
    "persistent-cache hit traces in well under a second; a miss pays "
    "the full XLA compile — the gap IS the cache's value)",
    buckets=COMPILE_BUCKETS,
)
boot_phase_seconds = REGISTRY.gauge(
    "janus_boot_phase_seconds",
    "wall seconds of each named bring-up phase on the last boot "
    "(imports, config, backend_init, datastore, engine_warm, "
    "listener_up; the full timeline is GET /debug/boot) — the "
    "cold-start regression gate",
)

# --- flight recorder: telemetry history + trend/leak verdicts
# (ISSUE 18; docs/OBSERVABILITY.md "Flight recorder and trend alerts") ---
flight_slope = REGISTRY.gauge(
    "janus_flight_slope",
    "robust (Theil-Sen) linear-regression slope of each leak-gated "
    "flight-recorder series over its trend window, in the series' "
    "units per second (bytes/s for the resource curves, rows/s for "
    "datastore_rows) — the number the endurance gates want at ~zero",
)
flight_leak_active = REGISTRY.gauge(
    "janus_flight_leak_active",
    "1 while a leak-gated flight-recorder series has a sustained "
    "positive trend clearing BOTH the residual noise band and the "
    "relative growth floor, else 0 — the `trend` SLO signal reads "
    "this, so a leak pages through the burn-rate ladder (/alertz)",
)
flight_p99_ratio = REGISTRY.gauge(
    "janus_flight_p99_ratio",
    "late-half over early-half p99 of each tracked latency family "
    "across the flight-recorder trend window (bucket-delta estimate) "
    "— the hour-1-vs-hour-N latency-stability gate; ~1.0 is stable",
)
flight_snapshots_total = REGISTRY.counter(
    "janus_flight_snapshots_total",
    "flight-recorder snapshot passes taken since process start",
)
flight_ring_bytes = REGISTRY.gauge(
    "janus_flight_ring_bytes",
    "on-disk bytes held by the flight-recorder JSONL segment ring "
    "(bounded by flight.max_total_bytes; 0 when memory-only)",
)
flight_ring_segments = REGISTRY.gauge(
    "janus_flight_ring_segments",
    "segment files in the flight-recorder on-disk ring",
)
flight_overhead_ratio = REGISTRY.gauge(
    "janus_flight_overhead_ratio",
    "measured fraction of wall time the flight recorder spends in its "
    "own snapshot + analysis passes (same self-accounting contract as "
    "janus_profiler_overhead_ratio; alert > 0.01)",
)

# --- lifecycle gauges the flight recorder tracks: GC progress,
# datastore row counts, on-disk artifact sizes (ISSUE 18 satellites) ---
gc_deleted_rows_total = REGISTRY.counter(
    "janus_gc_deleted_rows_total",
    "rows deleted by the garbage collector since process start, by "
    'kind ("reports" expired client reports, "aggregation" '
    'aggregation artifacts, "collection" collection artifacts) — '
    "under steady load this rises while janus_datastore_table_rows "
    "stays flat; both flat means GC is not keeping up is false, both "
    "rising means it is not running",
)
gc_tasks_scanned_total = REGISTRY.counter(
    "janus_gc_tasks_scanned_total",
    "tasks examined by garbage-collector passes since process start",
)
gc_runs_total = REGISTRY.counter(
    "janus_gc_runs_total",
    'garbage-collector passes, by outcome ("ok" | "error")',
)
gc_lag_seconds = REGISTRY.gauge(
    "janus_gc_lag_seconds",
    "seconds since the last completed garbage-collector pass (-1 "
    "until the first pass finishes) — a growing value with GC "
    "configured on means the pass is stuck or erroring",
)
datastore_table_rows = REGISTRY.gauge(
    "janus_datastore_table_rows",
    "rows per datastore table, sampled by the health sampler's "
    "periodic count transaction — the flight recorder's "
    "datastore_rows series sums this; flat under sustained load + GC "
    "is ROADMAP endurance gate #1",
)
artifact_bytes = REGISTRY.gauge(
    "janus_artifact_bytes",
    "on-disk bytes of each locally persisted artifact, sampled by the "
    'health sampler (artifact="upload_journal" spill-journal dir, '
    '"shape_manifest" dispatch-specialization manifest, "aot_cache" '
    "serialized-executable blob dir) — the flight recorder trends "
    "each for unbounded-growth leaks",
)

# --- report-flow conservation ledger (ISSUE 20; janus_tpu/ledger.py;
# docs/OBSERVABILITY.md "Conservation accounting") ---
ledger_imbalance = REGISTRY.gauge(
    "janus_ledger_imbalance",
    "per-(task, stage) report-flow conservation residual, evaluated "
    "at health-sampler cadence from the datastore-backed lifecycle "
    'counters: stage="ingest" is admitted - aggregated - rejected - '
    'expired - in-flight, stage="collect" is aggregated - collected - '
    "awaiting-collection. 0 means the books close; a sustained "
    "positive value is a silently lost report, a sustained negative "
    "one a double-count",
)
ledger_breach_active = REGISTRY.gauge(
    "janus_ledger_breach_active",
    "1 per (task, stage) whose conservation imbalance (or peer "
    'divergence, stage="peer") has been continuously nonzero longer '
    "than the ledger grace window — the conservation SLO signal's "
    "feed; transient read-snapshot skew between the counter and "
    "in-flight reads clears within the grace window and never sets it",
)
ledger_peer_divergence = REGISTRY.gauge(
    "janus_ledger_peer_divergence",
    "absolute difference between this leader's and the helper's "
    "per-batch aggregated report counts for the batches covered by a "
    "finished collection, from the helper's authenticated ledger "
    "reconciliation endpoint — the observability analog of a linear "
    "tag: 0 means both aggregators aggregated the same report mass",
)
ledger_evaluations_total = REGISTRY.counter(
    "janus_ledger_evaluations_total",
    'conservation-ledger evaluation passes, by outcome ("ok" | '
    '"error") — error passes keep the previous balance document and '
    "retry next tick",
)

# --- fleet scale-out: batched sharded lease claims + replica identity
# (ISSUE 15; docs/ARCHITECTURE.md "Running a fleet") ---
lease_acquire_tx_total = REGISTRY.counter(
    "janus_lease_acquire_tx_total",
    "batched lease-claim transactions run by the job drivers, by job "
    'kind and outcome (outcome="claimed" leased >= 1 job, "empty" '
    "found nothing eligible) — divide janus_lease_acquired_jobs_total "
    "by the claimed count for jobs-per-claim-roundtrip",
)
lease_acquired_jobs_total = REGISTRY.counter(
    "janus_lease_acquired_jobs_total",
    "jobs leased by the batched claim transactions, by job kind",
)
lease_steals_total = REGISTRY.counter(
    "janus_lease_steals_total",
    "leased jobs whose persisted shard key belongs to ANOTHER "
    "replica's shard (claimed through the steal-after-delay fallback), "
    "by job kind — a sustained nonzero rate means a replica is dead or "
    "starving and its shard is draining through its peers. Clean "
    "shutdown hand-backs (shard affinity released by a draining "
    "replica) are NOT counted: a routine rolling restart stays silent",
)
lease_conflicts_total = REGISTRY.counter(
    "janus_lease_conflicts_total",
    "token-guarded lease writes (release / step-back) that found the "
    "token no longer matching — the lease expired and another replica "
    "re-acquired the job — by job kind and op; zero in a healthy fleet "
    "(a nonzero rate means leases are outliving their work)",
)
replica_info = REGISTRY.gauge(
    "janus_replica_info",
    "constant 1, with this process's fleet identity as labels "
    "(replica_id/shard_index/shard_count) — join against it when N "
    "replicas export to one scrape plane",
)

_REPLICA_ID: str | None = None
_REPLICA_LABELED = False
_REPLICA_SHARD = (0, 1)  # (shard_index, shard_count)


def _fleet_status() -> dict:
    """Default /statusz `fleet` section (every process; janus_main
    replaces it with the richer config-aware one)."""
    return {
        "replica_id": replica_id(),
        "configured": _REPLICA_LABELED,
        "shard_index": _REPLICA_SHARD[0],
        "shard_count": _REPLICA_SHARD[1],
    }


def default_replica_id() -> str:
    """Stable-per-process fallback replica id (hostname-pid) used when
    no fleet identity is configured."""
    import socket

    return f"{socket.gethostname()}-{os.getpid()}"


def replica_labels() -> dict:
    """Per-replica labels for the job-driver/health-sampler/SLO metric
    families: {} until a fleet identity is EXPLICITLY configured
    (fleet.replica_id YAML / JANUS_REPLICA_ID env), so single-process
    deployments keep their exact label sets, and {"replica": id} in a
    fleet — N processes exporting to one scrape plane stay
    distinguishable."""
    if _REPLICA_LABELED and _REPLICA_ID:
        return {"replica": _REPLICA_ID}
    return {}


def set_replica_identity(
    replica_id: str | None = None,
    shard_index: int = 0,
    shard_count: int = 1,
    labeled: bool | None = None,
) -> None:
    """(Re-)populate janus_replica_info and set the per-replica label
    policy. `labeled` defaults to "a replica_id was explicitly given".
    The gauge is exclusive like janus_build_info: re-registration
    zeroes the previous label set."""
    global _REPLICA_ID, _REPLICA_LABELED, _REPLICA_SHARD
    explicit = replica_id is not None
    _REPLICA_ID = replica_id or default_replica_id()
    _REPLICA_LABELED = explicit if labeled is None else labeled
    # normalize like the claim predicate does (shard_index mod count):
    # the exported identity must name the shard the replica actually
    # claims, never a nonexistent out-of-range slice
    count = max(1, int(shard_count))
    shard_index = int(shard_index) % count
    shard_count = count
    _REPLICA_SHARD = (shard_index, shard_count)
    with replica_info._lock:
        for key in list(replica_info._values):
            replica_info._values[key] = 0.0
    replica_info.set(
        1,
        replica_id=_REPLICA_ID,
        shard_index=str(int(shard_index)),
        shard_count=str(int(shard_count)),
    )
    from .statusz import register_status_provider

    register_status_provider("fleet", _fleet_status)


def replica_id() -> str:
    """The process's current replica id (auto-generated until
    set_replica_identity installs a configured one)."""
    return _REPLICA_ID or default_replica_id()


# --- standard process/build families scrapers expect (janus_-prefixed
# per the repo naming lint; populated by register_build_info at import
# and refreshed by janus_main once the configured backend is known) ---
build_info = REGISTRY.gauge(
    "janus_build_info",
    "constant 1, with the build identity as labels "
    "(version/python/jax/backend) — join against it in dashboards",
)
process_start_time_seconds = REGISTRY.gauge(
    "janus_process_start_time_seconds",
    "unix time this process started (kernel starttime when /proc is "
    "available; import time otherwise) — rate() windows and restart "
    "detection key off it",
)

_IMPORT_TIME = time.time()


def _process_start_time() -> float:
    """Kernel-reported process start (field 22 of /proc/self/stat,
    ticks since boot, plus /proc/stat btime); falls back to this
    module's import time off Linux."""
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # comm may contain spaces/parens: fields start after the last ')'
        fields = stat.rsplit(")", 1)[1].split()
        start_ticks = float(fields[19])  # field 22 overall
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("btime "):
                    btime = float(line.split()[1])
                    break
            else:
                return _IMPORT_TIME
        return btime + start_ticks / os.sysconf("SC_CLK_TCK")
    except Exception:
        return _IMPORT_TIME


def register_build_info(backend: str | None = None) -> None:
    """(Re-)populate janus_build_info / janus_process_start_time_seconds.
    Called at import with the environment's backend guess; janus_main
    calls it again once the YAML-configured jax_platform is known. The
    gauge is exclusive: re-registering zeroes the previous label set so
    two backends never both read 1."""
    from . import __version__

    try:
        import importlib.metadata

        jax_version = importlib.metadata.version("jax")
    except Exception:
        jax_version = "unknown"
    with build_info._lock:
        for key in list(build_info._values):
            build_info._values[key] = 0.0
    build_info.set(
        1,
        version=__version__,
        python="%d.%d.%d" % sys.version_info[:3],
        jax=jax_version,
        backend=backend or os.environ.get("JAX_PLATFORMS", "") or "default",
    )
    process_start_time_seconds.set(_process_start_time())


register_build_info()
# auto identity at import (hostname-pid, UNLABELED): janus_replica_info
# always has exactly one value-1 sample; janus_main re-registers with
# the configured fleet identity (and turns per-replica labels on)
set_replica_identity()


def _register_span_bridges() -> None:
    """Bind the engine span names to janus_engine_dispatch_seconds via
    the span->metric bridge (trace.register_span_metric): a span exit
    IS the histogram observation, so the trace timeline and the metric
    cannot drift apart. The vdaf label rides the span args."""
    from .trace import register_span_metric

    for op in ("helper_init", "leader_init"):
        for span_name, phase in (
            (f"engine.{op}.put", "put"),
            (f"engine.{op}.dispatch", "dispatch"),
            (f"engine.{op}.fetch", "fetch"),
        ):
            register_span_metric(
                span_name,
                engine_dispatch_seconds,
                labels={"op": op, "phase": phase},
                arg_labels=("vdaf",),
            )
    # leader init's split fetches and the pipelined path's stages all
    # roll up into the same three phases
    for span_name, phase in (
        ("engine.leader_init.fetch_seed", "fetch"),
        ("engine.leader_init.fetch_ver", "fetch"),
        ("engine.leader_init.fetch_part", "fetch"),
        ("engine.leader_init.put_all_async", "put"),
        ("engine.leader_init.chunk", "dispatch"),
    ):
        register_span_metric(
            span_name,
            engine_dispatch_seconds,
            labels={"op": "leader_init", "phase": phase},
            arg_labels=("vdaf",),
        )
    register_span_metric(
        "engine.aggregate.dispatch",
        engine_dispatch_seconds,
        labels={"op": "aggregate", "phase": "dispatch"},
        arg_labels=("vdaf",),
    )


_register_span_bridges()
