"""Process metrics: counters/histograms + Prometheus text exposition.

Equivalent of the reference's OpenTelemetry metrics layer
(aggregator/src/metrics.rs:53-80 install_metrics_exporter with a
Prometheus or OTLP exporter; counter definitions like
janus_aggregate_step_failure_counter at aggregator.rs:114-154). Here a
dependency-free registry renders the Prometheus text format, served by
the health/metrics listener in janus_tpu.binary_utils.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from collections import defaultdict


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple[tuple[str, str], ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def add(self, n: float = 1, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += n

    def get(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0)

    def total(self) -> float:
        """Sum across all label sets (shed accounting in bench/tests)."""
        with self._lock:
            return sum(self._values.values())

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        for labels, v in items:
            lines.append(f"{self.name}{_fmt_labels(labels)} {v}")
        return "\n".join(lines)


class Gauge:
    """Instantaneous value (queue depths, in-flight counts)."""

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple[tuple[str, str], ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def set(self, v: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = v

    def add(self, n: float = 1, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += n

    def get(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        for labels, v in items:
            lines.append(f"{self.name}{_fmt_labels(labels)} {v}")
        return "\n".join(lines)


# The reference's custom boundaries for DB/HTTP latencies (metrics.rs)
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0,
)


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts: dict[tuple[tuple[str, str], ...], list[int]] = {}
        self._sums: dict[tuple[tuple[str, str], ...], float] = defaultdict(float)
        self._totals: dict[tuple[tuple[str, str], ...], int] = defaultdict(int)

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        # first bucket with bound >= value; == len(buckets) -> only +Inf
        idx = bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            if idx < len(self.buckets):
                counts[idx] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = sorted(self._counts)
            for key in keys:
                cum = 0
                for b, c in zip(self.buckets, self._counts[key]):
                    cum += c
                    lbl = _fmt_labels(key + (("le", f"{b:g}"),))
                    lines.append(f"{self.name}_bucket{lbl} {cum}")
                lines.append(
                    f'{self.name}_bucket{_fmt_labels(key + (("le", "+Inf"),))} {self._totals[key]}'
                )
                lines.append(f"{self.name}_sum{_fmt_labels(key)} {self._sums[key]}")
                lines.append(f"{self.name}_count{_fmt_labels(key)} {self._totals[key]}")
        return "\n".join(lines)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            assert isinstance(m, Counter)
            return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help_)
                self._metrics[name] = m
            assert isinstance(m, Gauge)
            return m

    def histogram(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets)
                self._metrics[name] = m
            assert isinstance(m, Histogram)
            return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"


REGISTRY = MetricsRegistry()

# Counters mirroring the reference's (aggregator.rs:114-245)
upload_decrypt_failure_counter = REGISTRY.counter(
    "janus_upload_decrypt_failures", "reports which failed HPKE decryption at upload"
)
upload_replay_counter = REGISTRY.counter(
    "janus_upload_replayed_reports", "Duplicate report uploads ignored"
)
upload_decode_failure_counter = REGISTRY.counter(
    "janus_upload_decode_failures", "reports which failed decoding at upload"
)
aggregate_step_failure_counter = REGISTRY.counter(
    "janus_aggregate_step_failures",
    "per-report failures during aggregation steps, by type",
)
job_cancel_counter = REGISTRY.counter(
    "janus_job_cancellations", "jobs abandoned after repeated failures"
)
engine_oom_retry_counter = REGISTRY.counter(
    "janus_engine_oom_retries",
    "device OOMs absorbed by halving the engine's batch bucket cap",
)
engine_host_fallback_counter = REGISTRY.counter(
    "janus_engine_host_fallbacks",
    "engines that hit the bucket floor on device OOM and fell back to the host engine",
)
http_request_counter = REGISTRY.counter(
    "janus_http_requests", "DAP HTTP requests by route and status"
)
http_request_duration = REGISTRY.histogram(
    "janus_http_request_duration_seconds", "DAP HTTP request latency"
)
tx_duration = REGISTRY.histogram(
    "janus_database_transaction_duration_seconds", "datastore transaction latency"
)
# --- ingest pipeline (janus_tpu.ingest; docs/INGEST.md) ---
upload_shed_counter = REGISTRY.counter(
    "janus_upload_shed_total",
    "requests rejected 429 by the admission controller, by route and reason",
)
ingest_queue_depth = REGISTRY.gauge(
    "janus_ingest_queue_depth", "ingest pipeline stage queue depths, by stage"
)
ingest_inflight = REGISTRY.gauge(
    "janus_ingest_inflight", "uploads admitted and not yet committed/failed"
)
ingest_stage_duration = REGISTRY.histogram(
    "janus_ingest_stage_duration_seconds",
    "per-report ingest stage latency (decode, decrypt, commit), by stage",
)
