"""Recorded-conversation fake of the psycopg driver surface.

This image has no Postgres server and no psycopg, so the
`PostgresDatastore` engine — the horizontal-scaling deployment story
(reference aggregator_core/src/datastore.rs:203-305) — would otherwise
never execute. This driver stands in for psycopg at the exact seam
`PostgresDatastore` uses (`connect`, `IsolationLevel`, `errors.*`,
`OperationalError`), so the PG adapter's Python logic — `%s` parameter
binding, implicit-BEGIN transaction management, REPEATABLE-READ retry
loop, broken-connection discard, advisory-lock bootstrap, FOR UPDATE
SKIP LOCKED lease claims — runs for real, in-image, against a shared
SQLite file that plays the server.

Two layers of fidelity:

- **Conversation**: every statement is recorded exactly as it would hit
  the PG wire (after the adapter's `?`→`%s` rewrite), plus
  connect/commit/rollback/close events. Tests assert the exact SQL +
  parameter streams for the lease and retry paths
  (tests/test_pg_conversation.py), the analog of the reference proving
  those paths against its ephemeral postgres container
  (datastore/test_util.rs:26-120).
- **Execution**: statements are translated back (`%s`→`?`, PG-only
  statements mapped to no-ops) and executed on SQLite, so typed ops see
  real rows and the full datastore suite runs against the PG engine
  (conftest DATASTORE_ENGINES includes "pgfake" unconditionally).

What this cannot prove: genuine PG server semantics (MVCC snapshot
behavior, serialization-failure timing, type coercion details). For
that, `docker-compose.pg.yaml` + JANUS_TEST_DATABASE_URL runs the same
suite against a real server (conftest adds the "postgres" engine
automatically when psycopg and the URL are present).

Error taxonomy mirrors psycopg's: SerializationFailure and
DeadlockDetected subclass OperationalError, which subclasses Error.
SQLite "database is locked" surfaces as OperationalError — the same
retryable class a PG worker sees on a dropped connection.
"""

from __future__ import annotations

import os
import re
import sqlite3
import tempfile
import threading


class Error(Exception):
    pass


class OperationalError(Error):
    pass


class IntegrityError(Error):
    pass


class SerializationFailure(OperationalError):
    pass


class DeadlockDetected(OperationalError):
    pass


class InFailedSqlTransaction(Error):
    pass


class _Errors:
    """The `psycopg.errors` namespace subset the datastore touches."""

    SerializationFailure = SerializationFailure
    DeadlockDetected = DeadlockDetected
    IntegrityError = IntegrityError
    InFailedSqlTransaction = InFailedSqlTransaction


class _IsolationLevel:
    READ_COMMITTED = 1
    REPEATABLE_READ = 2
    SERIALIZABLE = 3


_ADVISORY_LOCK_RE = re.compile(r"^\s*SELECT\s+pg_advisory_xact_lock", re.I)
_CREATE_SCHEMA_RE = re.compile(r"^\s*CREATE\s+SCHEMA\b", re.I)
_DROP_SCHEMA_RE = re.compile(r"^\s*DROP\s+SCHEMA\b", re.I)
# PG row-locking clause SQLite has no parse for; recorded verbatim,
# stripped for execution (SQLite's database-level write lock is the
# stand-in — the real SKIP LOCKED semantics need the real-PG suite).
# Matched at statement end OR at a subquery's closing paren: the
# batched lease claim puts it INSIDE the candidate subquery
# (UPDATE .. WHERE (..) IN (SELECT .. FOR UPDATE SKIP LOCKED)).
_FOR_UPDATE_RE = re.compile(r"\s+FOR\s+UPDATE(\s+SKIP\s+LOCKED)?(?=\s*\)|\s*$)", re.I)


def _to_sqlite(sql: str) -> str:
    return _FOR_UPDATE_RE.sub("", sql).replace("%s", "?")


# UPDATE ... RETURNING needs SQLite >= 3.35; on older system libs the
# fake emulates it (see FakeConnection._execute_update_returning) so
# the recorded PG wire form never changes.
_SQLITE_RETURNING = sqlite3.sqlite_version_info >= (3, 35)
_UPDATE_RETURNING_RE = re.compile(
    r"^\s*(UPDATE\s+(\w+)\s+SET\s+.+?)\s+RETURNING\s+(.+?)\s*$", re.I | re.S
)


def _depth0_where(s: str) -> int:
    """Index of the outermost ' WHERE ' (paren depth 0), or -1."""
    depth = 0
    u = s.upper()
    for i, c in enumerate(s):
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif depth == 0 and u.startswith(" WHERE ", i):
            return i
    return -1


class FakeConnection:
    """psycopg-Connection surface: execute/cursor/commit/rollback/close,
    `closed`/`broken` flags, assignable `isolation_level`. Transactions
    are implicit (BEGIN at first statement), matching psycopg
    autocommit=False."""

    def __init__(self, driver: "FakePostgresDriver"):
        self._driver = driver
        self._sq = sqlite3.connect(
            driver._db_path, timeout=5.0, isolation_level=None, check_same_thread=False
        )
        self._sq.execute("PRAGMA foreign_keys=ON")
        self._in_tx = False
        self.closed = False
        self.broken = False
        self.isolation_level = None

    # -- transaction management (implicit BEGIN, like psycopg) --
    def _ensure_tx(self):
        if not self._in_tx:
            self._sq.execute("BEGIN")
            self._in_tx = True

    def execute(self, sql: str, params=()):
        self._driver._record("execute", sql, tuple(params))
        if self.broken or self.closed:
            raise OperationalError("connection is broken")
        self._driver._maybe_inject(self, sql, params)
        if _ADVISORY_LOCK_RE.match(sql):
            self._ensure_tx()
            return self._sq.execute("SELECT 1")
        if _CREATE_SCHEMA_RE.match(sql) or _DROP_SCHEMA_RE.match(sql):
            self._ensure_tx()
            return self._sq.execute("SELECT 1")
        self._ensure_tx()
        try:
            if not _SQLITE_RETURNING:
                m = _UPDATE_RETURNING_RE.match(sql)
                if m:
                    return self._execute_update_returning(m, tuple(params))
            return self._sq.execute(_to_sqlite(sql), params)
        except sqlite3.IntegrityError:
            raise  # _INTEGRITY_ERRORS catches the sqlite3 class
        except sqlite3.OperationalError as e:
            raise OperationalError(str(e)) from e

    def _execute_update_returning(self, m: "re.Match", params: tuple):
        """UPDATE ... RETURNING on a pre-3.35 sqlite: pin the matching
        rowids first, update only those, then select the RETURNING
        columns back by rowid. Equivalent inside the surrounding
        transaction (single writer); the conversation log above already
        recorded the genuine PG wire form."""
        head, table, cols = m.group(1), m.group(2), m.group(3)
        wi = _depth0_where(head)
        set_part, where = (head[:wi], head[wi + 7 :]) if wi >= 0 else (head, None)
        n_set = set_part.count("%s")
        if where is None:
            sel = f"SELECT rowid FROM {table}"  # noqa: S608 - fake, test-only
            rowids = [r[0] for r in self._sq.execute(sel).fetchall()]
        else:
            sel = f"SELECT rowid FROM {table} WHERE {_to_sqlite(where)}"
            rowids = [r[0] for r in self._sq.execute(sel, params[n_set:]).fetchall()]
        if not rowids:
            return self._sq.execute(f"SELECT {_to_sqlite(cols)} FROM {table} WHERE 0")
        ph = ",".join("?" * len(rowids))
        self._sq.execute(
            f"{_to_sqlite(set_part)} WHERE rowid IN ({ph})",
            params[:n_set] + tuple(rowids),
        )
        return self._sq.execute(
            f"SELECT {_to_sqlite(cols)} FROM {table} WHERE rowid IN ({ph})",
            tuple(rowids),
        )

    def cursor(self):
        conn = self

        class _Cur:
            def executemany(self, sql, seq):
                seq = [tuple(p) for p in seq]
                conn._driver._record("executemany", sql, tuple(seq))
                if conn.broken or conn.closed:
                    raise OperationalError("connection is broken")
                conn._driver._maybe_inject(conn, sql, seq)
                conn._ensure_tx()
                try:
                    self._c = conn._sq.executemany(_to_sqlite(sql), seq)
                except sqlite3.IntegrityError:
                    raise
                except sqlite3.OperationalError as e:
                    raise OperationalError(str(e)) from e
                return self._c

            def __getattr__(self, name):
                # Guard: before executemany() runs there is no `_c`, and
                # a bare `getattr(self._c, ...)` would re-enter this
                # __getattr__ for `_c` itself — infinite recursion
                # surfacing as RecursionError (round-5 advisory).
                if name == "_c":
                    raise AttributeError(
                        "cursor has no result yet: call executemany() first"
                    )
                return getattr(self._c, name)

        return _Cur()

    def commit(self):
        self._driver._record("commit")
        if self.broken or self.closed:
            raise OperationalError("connection is broken")
        if self._in_tx:
            self._sq.execute("COMMIT")
            self._in_tx = False

    def rollback(self):
        self._driver._record("rollback")
        if self.broken or self.closed:
            raise OperationalError("connection is broken")
        if self._in_tx:
            self._sq.execute("ROLLBACK")
            self._in_tx = False

    def close(self):
        self._driver._record("close")
        self.closed = True
        try:
            self._sq.close()
        except Exception:
            pass


class FakePostgresDriver:
    """Module-shaped driver object: pass as `PostgresDatastore(driver=...)`."""

    errors = _Errors
    OperationalError = OperationalError
    Error = Error
    IsolationLevel = _IsolationLevel

    def __init__(self, db_path: str | None = None):
        if db_path is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="janus-pgfake-")
            db_path = os.path.join(self._tmp.name, "pgfake.sqlite")
        else:
            self._tmp = None
        self._db_path = db_path
        self._lock = threading.Lock()
        self.log: list[tuple] = []
        self.connections: list[FakeConnection] = []
        # (predicate(sql, params) -> bool, exception, once) injection
        # rules, checked before execution — tests script failures here
        self._injections: list[list] = []

    # -- psycopg module surface --
    def connect(self, dsn: str, autocommit: bool = False, **kwargs):
        self._record("connect", dsn, tuple(sorted(kwargs)))
        assert autocommit is False, "datastore always runs transactional"
        conn = FakeConnection(self)
        self.connections.append(conn)
        return conn

    # -- recording / scripting --
    def _record(self, kind: str, *detail):
        with self._lock:
            self.log.append((kind, *detail))

    def _maybe_inject(self, conn, sql, params):
        with self._lock:
            for rule in self._injections:
                pred, exc, once, break_conn = rule
                if pred(sql, params):
                    if once:
                        self._injections.remove(rule)
                    if break_conn:
                        # model a dropped server connection: psycopg
                        # marks the connection broken and every later
                        # operation on it (rollback included) fails
                        conn.broken = True
                    raise exc

    def inject_once(self, predicate, exc: Exception, break_connection: bool = False):
        """Raise `exc` on the first statement matching predicate(sql,
        params). With break_connection=True the connection is marked
        broken first (the dropped-mid-transaction shape: the datastore
        must discard it and redial, never retry into it)."""
        self._injections.append([predicate, exc, True, break_connection])

    def statements(self, kind: str = "execute") -> list[tuple]:
        return [e for e in self.log if e[0] == kind]

    def clear_log(self):
        with self._lock:
            self.log.clear()

    def cleanup(self):
        for c in self.connections:
            if not c.closed:
                try:
                    c.close()
                except Exception:
                    pass
        if self._tmp is not None:
            self._tmp.cleanup()
