"""Datastore: transactional facade + typed ops + Crypter.

Equivalent of reference aggregator_core/src/datastore.rs:107-4960.
Two engines behind one typed-op surface (the reference's horizontal
scaling is Postgres, datastore.rs:203-305; SQLite serves single-host
deployments and tests):

  - SQLite: `run_tx` retry on serialization failure
    (datastore.rs:216-305) -> BEGIN IMMEDIATE + bounded retry on
    SQLITE_BUSY; lease acquire (`FOR UPDATE SKIP LOCKED`,
    datastore.rs:1836-1905) -> guarded UPDATE ... RETURNING per claim,
    atomic under SQLite's writer lock.
  - Postgres (`PostgresDatastore`, psycopg): REPEATABLE READ +
    retry-on-serialization-failure, real `FOR UPDATE SKIP LOCKED`
    lease claims, same schema translated BLOB->BYTEA/INTEGER->BIGINT.
    Selected by `database.url` = postgres:// (open_datastore).
  - `Crypter` AES-128-GCM encryption at rest with AAD =
    table||row||column and multi-key rotation (datastore.rs:4889-4960)
    — engine-independent.

The typed ops (Transaction) are written once in portable SQL; the
engine differences are confined to placeholder style (adapter), the
integrity-error types, the lease-select locking suffix, and DDL types.
"""

from __future__ import annotations

import logging
import os
import re
import secrets
import sqlite3
import tempfile
import threading
import time as _time

_log = logging.getLogger(__name__)

try:  # Postgres backend is optional (psycopg not present in all images)
    import psycopg as _psycopg
except ImportError:  # pragma: no cover - exercised where psycopg exists
    _psycopg = None

_INTEGRITY_ERRORS = (
    (sqlite3.IntegrityError,)
    if _psycopg is None
    else (sqlite3.IntegrityError, _psycopg.errors.IntegrityError)
)

from ..core.hpke_backend import AESGCM

from ..messages import (
    AggregationJobId,
    BatchId,
    CollectionJobId,
    HpkeCiphertext,
    Interval,
    PrepareError,
    Duration,
    ReportId,
    ReportIdChecksum,
    TaskId,
    Time,
)
from ..task import Task
from .models import (
    AcquiredAggregationJob,
    AcquiredCollectionJob,
    AggregateShareJob,
    AggregationJobModel,
    AggregationJobState,
    Batch,
    BatchAggregation,
    BatchAggregationState,
    BatchState,
    CollectionJobModel,
    CollectionJobState,
    LeaderStoredReport,
    Lease,
    OutstandingBatch,
    ReportAggregationModel,
    ReportAggregationState,
    ShardSpec,
)

SCHEMA_VERSION = 5

# POSTGRES TRANSLATION CONSTRAINTS (tests/test_pg_dialect.py enforces):
# the Postgres engine derives its DDL from this exact text via
# word-bounded BLOB->BYTEA / INTEGER->BIGINT rewrites, and the typed
# ops' SQL gets a blind '?'->'%s' placeholder rewrite. Therefore no
# identifier here may contain the words BLOB or INTEGER, and no SQL
# string literal anywhere in this module may contain a literal '?'.
_SCHEMA = """
CREATE TABLE IF NOT EXISTS schema_version (version INTEGER NOT NULL);

CREATE TABLE IF NOT EXISTS tasks (
    task_id BLOB PRIMARY KEY,
    role INTEGER NOT NULL,
    task_expiration INTEGER,
    doc BLOB NOT NULL            -- encrypted serialized Task
);

CREATE TABLE IF NOT EXISTS client_reports (
    task_id BLOB NOT NULL,
    report_id BLOB NOT NULL,
    client_time INTEGER NOT NULL,
    public_share BLOB,
    leader_input_share BLOB,     -- encrypted
    helper_encrypted_input_share BLOB,
    aggregation_started INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (task_id, report_id)
);
-- partial-index analog of ...up.sql:157 (unaggregated lookup)
CREATE INDEX IF NOT EXISTS client_reports_unaggregated
    ON client_reports (task_id, client_time) WHERE aggregation_started = 0;

CREATE TABLE IF NOT EXISTS aggregation_jobs (
    task_id BLOB NOT NULL,
    job_id BLOB NOT NULL,
    aggregation_parameter BLOB NOT NULL,
    partial_batch_identifier BLOB NOT NULL,
    client_interval_start INTEGER NOT NULL,
    client_interval_duration INTEGER NOT NULL,
    state TEXT NOT NULL,
    step INTEGER NOT NULL DEFAULT 0,
    last_request_hash BLOB,
    trace_context TEXT,          -- W3C traceparent of the creating span
    shard_key INTEGER NOT NULL DEFAULT 0,  -- job_shard_key(task, job)
    lease_expiry INTEGER NOT NULL DEFAULT 0,
    lease_token BLOB,
    lease_attempts INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (task_id, job_id)
);
-- analog of the state_and_lease_expiry index (...up.sql:168-189)
CREATE INDEX IF NOT EXISTS aggregation_jobs_lease
    ON aggregation_jobs (state, lease_expiry) WHERE state = 'in_progress';

CREATE TABLE IF NOT EXISTS report_aggregations (
    task_id BLOB NOT NULL,
    job_id BLOB NOT NULL,
    report_id BLOB NOT NULL,
    client_time INTEGER NOT NULL,
    ord INTEGER NOT NULL,
    state TEXT NOT NULL,
    prep_blob BLOB,              -- encrypted
    prepare_error INTEGER,
    PRIMARY KEY (task_id, job_id, ord)
);
CREATE INDEX IF NOT EXISTS report_aggregations_by_report
    ON report_aggregations (task_id, report_id);

CREATE TABLE IF NOT EXISTS batch_aggregations (
    task_id BLOB NOT NULL,
    batch_identifier BLOB NOT NULL,
    aggregation_parameter BLOB NOT NULL,
    ord INTEGER NOT NULL,
    state TEXT NOT NULL,
    aggregate_share BLOB,
    report_count INTEGER NOT NULL DEFAULT 0,
    client_interval_start INTEGER NOT NULL DEFAULT 0,
    client_interval_duration INTEGER NOT NULL DEFAULT 0,
    checksum BLOB NOT NULL,
    PRIMARY KEY (task_id, batch_identifier, aggregation_parameter, ord)
);

CREATE TABLE IF NOT EXISTS collection_jobs (
    task_id BLOB NOT NULL,
    collection_job_id BLOB NOT NULL,
    query BLOB NOT NULL,
    aggregation_parameter BLOB NOT NULL,
    batch_identifier BLOB NOT NULL,
    state TEXT NOT NULL,
    report_count INTEGER,
    client_interval_start INTEGER,
    client_interval_duration INTEGER,
    leader_aggregate_share BLOB,           -- encrypted
    helper_encrypted_aggregate_share BLOB,
    trace_context TEXT,          -- W3C traceparent of the creating span
    shard_key INTEGER NOT NULL DEFAULT 0,  -- job_shard_key(task, job)
    lease_expiry INTEGER NOT NULL DEFAULT 0,
    lease_token BLOB,
    lease_attempts INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (task_id, collection_job_id)
);

CREATE TABLE IF NOT EXISTS aggregate_share_jobs (
    task_id BLOB NOT NULL,
    batch_identifier BLOB NOT NULL,
    aggregation_parameter BLOB NOT NULL,
    helper_aggregate_share BLOB NOT NULL,  -- encrypted
    report_count INTEGER NOT NULL,
    checksum BLOB NOT NULL,
    PRIMARY KEY (task_id, batch_identifier, aggregation_parameter)
);

CREATE TABLE IF NOT EXISTS batches (
    task_id BLOB NOT NULL,
    batch_identifier BLOB NOT NULL,
    aggregation_parameter BLOB NOT NULL,
    state TEXT NOT NULL,
    outstanding_aggregation_jobs INTEGER NOT NULL DEFAULT 0,
    client_interval_start INTEGER NOT NULL DEFAULT 0,
    client_interval_duration INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (task_id, batch_identifier, aggregation_parameter)
);

CREATE TABLE IF NOT EXISTS outstanding_batches (
    task_id BLOB NOT NULL,
    batch_id BLOB NOT NULL,
    time_bucket_start INTEGER,
    size INTEGER NOT NULL DEFAULT 0,     -- reports assigned so far
    filled INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (task_id, batch_id)
);

CREATE TABLE IF NOT EXISTS global_hpke_keys (
    config_id INTEGER PRIMARY KEY,
    config BLOB NOT NULL,
    private_key BLOB NOT NULL,   -- encrypted
    state TEXT NOT NULL DEFAULT 'pending',
    updated_at INTEGER NOT NULL
);

CREATE TABLE IF NOT EXISTS taskprov_peer_aggregators (
    endpoint TEXT NOT NULL,
    role INTEGER NOT NULL,
    doc BLOB NOT NULL,           -- encrypted serialized PeerAggregator
    PRIMARY KEY (endpoint, role)
);

-- Report-flow conservation ledger (janus_tpu/ledger.py): monotone
-- per-task lifecycle counters, incremented INSIDE the same transaction
-- as the state change they count — run_tx retries re-run the whole
-- closure, so a counter updated in the tx is exactly-once, and every
-- process (listener, driver fleet, GC) sees one consistent set of
-- books. Bounded: O(tasks x counter names), never per-report.
CREATE TABLE IF NOT EXISTS task_counters (
    task_id BLOB NOT NULL,
    counter_name TEXT NOT NULL,
    amount INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (task_id, counter_name)
);
"""


class Crypter:
    """AES-128-GCM at rest, AAD = table||row||column, multi-key rotation
    (reference datastore.rs:4889-4960): encrypt under keys[0], try all
    keys on decrypt."""

    NONCE = 12

    def __init__(self, keys: list[bytes] | None = None):
        keys = keys if keys is not None else [secrets.token_bytes(16)]
        assert keys and all(len(k) == 16 for k in keys)
        self._keys = [AESGCM(k) for k in keys]

    @staticmethod
    def aad(table: str, row: bytes, column: str) -> bytes:
        return table.encode() + b"/" + row + b"/" + column.encode()

    def encrypt(self, table: str, row: bytes, column: str, plaintext: bytes) -> bytes:
        nonce = secrets.token_bytes(self.NONCE)
        return nonce + self._keys[0].encrypt(nonce, plaintext, self.aad(table, row, column))

    def decrypt(self, table: str, row: bytes, column: str, data: bytes) -> bytes:
        nonce, ct = data[: self.NONCE], data[self.NONCE :]
        aad = self.aad(table, row, column)
        last = None
        for key in self._keys:
            try:
                return key.decrypt(nonce, ct, aad)
            except Exception as e:  # InvalidTag
                last = e
        raise ValueError(f"datastore decryption failed: {last}")


class TxConflict(Exception):
    pass


class LeaseConflict(TxConflict):
    """A token-guarded lease write (release / step-back) found the
    token no longer matching: the lease expired and another replica
    re-acquired it. Deterministic — classified "fatal" so run_tx
    raises immediately instead of burning its retry budget on a
    mismatch no retry can fix — and counted in
    janus_lease_conflicts_total{kind,op} so a fleet losing claim races
    is visible instead of invisible."""


# ---------------------------------------------------------------------------
# Fleet sharding + lease-token provenance (docs/ARCHITECTURE.md
# "Running a fleet"). The shard key is persisted on every job row at
# creation so the batched claim's shard predicate is plain integer
# arithmetic — portable to sqlite, pg_fake and real Postgres alike.
# ---------------------------------------------------------------------------

# modulo space of the persisted shard hash: far above any plausible
# shard_count, small enough that `shard_key % count` stays exact in
# every engine's integer type
SHARD_KEY_SPACE = 1 << 16


def job_shard_key(task_id: bytes, job_id: bytes) -> int:
    """Stable shard hash of a (task, job) identity, persisted on the
    row at creation. sha256-based so every replica — any language, any
    PYTHONHASHSEED — computes the same key."""
    import hashlib

    digest = hashlib.sha256(task_id + job_id).digest()
    return int.from_bytes(digest[:8], "big") % SHARD_KEY_SPACE


def replica_holder_tag(replica_id: str) -> bytes:
    """8-byte stable provenance tag of a replica id, carried in the
    first half of every lease token the replica mints."""
    import hashlib

    return hashlib.sha256(replica_id.encode()).digest()[:8]


def make_lease_token(holder: bytes | None = None) -> bytes:
    """Fresh 16-byte lease token. With a holder tag the first 8 bytes
    carry the claiming replica's provenance (lease_holder_hex reads it
    back off a held row) and the last 8 stay random per claim
    transaction — token uniqueness per claim generation is what the
    guarded release/step-back need; the row identity does the rest."""
    if holder:
        return bytes(holder[:8]).ljust(8, b"\0") + secrets.token_bytes(8)
    return secrets.token_bytes(16)


def lease_holder_hex(token: bytes | None) -> str | None:
    """Provenance half of a lease token (hex), or None when no lease
    is held. Only meaningful for tokens minted with a holder tag."""
    return bytes(token[:8]).hex() if token else None


# shard_key sentinel for a clean shutdown hand-back: the draining
# replica RELEASES the row's shard affinity, so ANY surviving replica
# — any shard, any steal_after — claims it immediately instead of
# waiting out the steal fence meant for rows whose holder DIED; and
# because the claim returns the stored shard_key, a hand-back claim is
# distinguishable from a genuine steal (janus_lease_steals_total must
# not fire on every routine rolling restart).
HANDBACK_SHARD_KEY = -1


class _PgConnAdapter:
    """Gives a psycopg connection the sqlite3 execute surface the typed
    ops are written against: qmark placeholders, execute returning a
    cursor with fetchone/fetchall/rowcount."""

    def __init__(self, conn):
        self._conn = conn

    def execute(self, sql: str, params=()):
        return self._conn.execute(sql.replace("?", "%s"), params)

    def executemany(self, sql: str, seq):
        cur = self._conn.cursor()
        cur.executemany(sql.replace("?", "%s"), list(seq))
        return cur


class Transaction:
    """One open transaction; exposes every typed op. Obtained from
    Datastore.run_tx / Datastore.tx(). The ops are portable SQL; the
    `dialect` selects the lease-select locking suffix (Postgres gets a
    real FOR UPDATE SKIP LOCKED, datastore.rs:1853-1860)."""

    def __init__(self, conn, crypter: Crypter, clock, dialect: str = "sqlite"):
        self._c = conn
        self._crypter = crypter
        self._clock = clock
        self._lease_suffix = " FOR UPDATE SKIP LOCKED" if dialect == "postgres" else ""
        # UPDATE ... RETURNING needs SQLite >= 3.35 (2021); older system
        # libs (this image ships 3.34) take the two-statement fallback.
        # Safe: every op already runs inside one serialized transaction
        # on one connection, so SELECT-then-UPDATE cannot interleave.
        # Postgres always keeps the RETURNING wire form (pg_fake
        # emulates it on old sqlite so the recorded conversation is
        # byte-identical to what production postgres receives).
        self._returning = dialect == "postgres" or sqlite3.sqlite_version_info >= (3, 35)

    def _update_returning_one(
        self, update_sql: str, params, returning: str, select_sql: str, select_params
    ):
        """Single-row guarded `UPDATE ... RETURNING <returning>`, with
        the pre-3.35-sqlite two-statement form: UPDATE, then re-read via
        select_sql only when a row was changed. Exact inside the
        serialized transaction (see _returning above). New
        UPDATE...RETURNING call sites should use this instead of
        hand-rolling the fallback pair."""
        if self._returning:
            return self._c.execute(update_sql + " RETURNING " + returning, params).fetchone()
        if not self._c.execute(update_sql, params).rowcount:
            return None
        return self._c.execute(select_sql, select_params).fetchone()

    # ---- tasks (reference datastore.rs:528-1160) ----
    def put_task(self, task: Task) -> None:
        import json

        doc = json.dumps(task.to_dict()).encode()
        enc = self._crypter.encrypt("tasks", task.task_id.data, "doc", doc)
        self._c.execute(
            "INSERT INTO tasks (task_id, role, task_expiration, doc) VALUES (?,?,?,?)",
            (
                task.task_id.data,
                int(task.role),
                task.task_expiration.seconds if task.task_expiration else None,
                enc,
            ),
        )

    def get_task(self, task_id: TaskId) -> Task | None:
        import json

        row = self._c.execute(
            "SELECT doc FROM tasks WHERE task_id = ?", (task_id.data,)
        ).fetchone()
        if row is None:
            return None
        doc = self._crypter.decrypt("tasks", task_id.data, "doc", row[0])
        return Task.from_dict(json.loads(doc))

    def get_task_ids(self) -> list[TaskId]:
        return [
            TaskId(r[0]) for r in self._c.execute("SELECT task_id FROM tasks ORDER BY task_id")
        ]

    def get_tasks(self) -> list[Task]:
        return [t for t in (self.get_task(tid) for tid in self.get_task_ids()) if t]

    def delete_task(self, task_id: TaskId) -> None:
        for table in (
            "tasks",
            "client_reports",
            "aggregation_jobs",
            "report_aggregations",
            "batch_aggregations",
            "collection_jobs",
            "aggregate_share_jobs",
            "batches",
            "outstanding_batches",
        ):
            self._c.execute(f"DELETE FROM {table} WHERE task_id = ?", (task_id.data,))

    # ---- taskprov peer aggregators (reference datastore.rs:4436-4748) ----
    def put_taskprov_peer_aggregator(self, peer) -> None:
        import json

        row_key = peer.endpoint.encode() + bytes([int(peer.role)])
        doc = json.dumps(peer.to_dict()).encode()
        enc = self._crypter.encrypt("taskprov_peer_aggregators", row_key, "doc", doc)
        # upsert portable to both engines (sqlite >= 3.24 and Postgres)
        self._c.execute(
            "INSERT INTO taskprov_peer_aggregators (endpoint, role, doc)"
            " VALUES (?,?,?)"
            " ON CONFLICT (endpoint, role) DO UPDATE SET doc = excluded.doc",
            (peer.endpoint, int(peer.role), enc),
        )

    def _decode_peer_aggregator(self, endpoint: str, role: int, doc_enc: bytes):
        import json

        from ..taskprov import PeerAggregator

        row_key = endpoint.encode() + bytes([int(role)])
        doc = self._crypter.decrypt("taskprov_peer_aggregators", row_key, "doc", doc_enc)
        return PeerAggregator.from_dict(json.loads(doc))

    def get_taskprov_peer_aggregator(self, endpoint: str, role):
        row = self._c.execute(
            "SELECT doc FROM taskprov_peer_aggregators WHERE endpoint = ? AND role = ?",
            (endpoint, int(role)),
        ).fetchone()
        if row is None:
            return None
        return self._decode_peer_aggregator(endpoint, int(role), row[0])

    def get_taskprov_peer_aggregators(self) -> list:
        rows = self._c.execute(
            "SELECT endpoint, role, doc FROM taskprov_peer_aggregators ORDER BY endpoint, role"
        ).fetchall()
        return [self._decode_peer_aggregator(e, r, d) for e, r, d in rows]

    def delete_taskprov_peer_aggregator(self, endpoint: str, role) -> None:
        self._c.execute(
            "DELETE FROM taskprov_peer_aggregators WHERE endpoint = ? AND role = ?",
            (endpoint, int(role)),
        )

    # ---- client reports (reference datastore.rs:1162-1723) ----
    def put_client_report(self, report: LeaderStoredReport) -> bool:
        """Returns False if the report id already exists (replay)."""
        row_key = report.task_id.data + report.report_id.data
        lis = self._crypter.encrypt(
            "client_reports", row_key, "leader_input_share", report.leader_input_share
        )
        # ON CONFLICT DO NOTHING instead of catch-and-continue: a caught
        # IntegrityError would poison a Postgres transaction (everything
        # after it fails with InFailedSqlTransaction), and the report
        # writer keeps using the tx for the rest of its batch.
        cur = self._c.execute(
            "INSERT INTO client_reports (task_id, report_id, client_time, public_share,"
            " leader_input_share, helper_encrypted_input_share) VALUES (?,?,?,?,?,?)"
            " ON CONFLICT DO NOTHING",
            (
                report.task_id.data,
                report.report_id.data,
                report.client_time.seconds,
                report.public_share,
                lis,
                report.helper_encrypted_input_share.to_bytes(),
            ),
        )
        return cur.rowcount == 1

    def delete_client_report(self, task_id: TaskId, report_id: ReportId) -> bool:
        """Delete one stored report row. Production code never calls
        this — it exists for the `ledger.drop_report` chaos failpoint
        (inject a silent loss AFTER the admission counter booked the
        report, so the conservation ledger must catch it) and for test
        harnesses. Returns True if a row was deleted."""
        cur = self._c.execute(
            "DELETE FROM client_reports WHERE task_id = ? AND report_id = ?",
            (task_id.data, report_id.data),
        )
        return cur.rowcount == 1

    def get_client_report(self, task_id: TaskId, report_id: ReportId) -> LeaderStoredReport | None:
        row = self._c.execute(
            "SELECT client_time, public_share, leader_input_share, helper_encrypted_input_share"
            " FROM client_reports WHERE task_id = ? AND report_id = ?",
            (task_id.data, report_id.data),
        ).fetchone()
        if row is None:
            return None
        row_key = task_id.data + report_id.data
        return LeaderStoredReport(
            task_id,
            report_id,
            Time(row[0]),
            row[1],
            self._crypter.decrypt("client_reports", row_key, "leader_input_share", row[2]),
            HpkeCiphertext.from_bytes(row[3]),
        )

    def check_report_replayed(self, task_id: TaskId, report_id: ReportId) -> bool:
        return (
            self._c.execute(
                "SELECT 1 FROM client_reports WHERE task_id = ? AND report_id = ?",
                (task_id.data, report_id.data),
            ).fetchone()
            is not None
        )

    def get_unaggregated_client_reports_for_task(
        self, task_id: TaskId, limit: int
    ) -> list[tuple[ReportId, Time]]:
        """Claims up to `limit` unaggregated reports (marks them started),
        like datastore.rs:1331 get_unaggregated_client_report_ids_for_task."""
        if self._returning:
            rows = self._c.execute(
                "UPDATE client_reports SET aggregation_started = 1"
                " WHERE (task_id, report_id) IN ("
                "   SELECT task_id, report_id FROM client_reports"
                "   WHERE task_id = ? AND aggregation_started = 0"
                "   ORDER BY client_time LIMIT ?)"
                " RETURNING report_id, client_time",
                (task_id.data, limit),
            ).fetchall()
        else:
            rows = self._c.execute(
                "SELECT report_id, client_time FROM client_reports"
                " WHERE task_id = ? AND aggregation_started = 0"
                " ORDER BY client_time LIMIT ?",
                (task_id.data, limit),
            ).fetchall()
            self._c.executemany(
                "UPDATE client_reports SET aggregation_started = 1"
                " WHERE task_id = ? AND report_id = ?",
                [(task_id.data, r[0]) for r in rows],
            )
        return [(ReportId(r[0]), Time(r[1])) for r in rows]

    def mark_reports_unaggregated(self, task_id: TaskId, report_ids: list[ReportId]) -> None:
        self._c.executemany(
            "UPDATE client_reports SET aggregation_started = 0 WHERE task_id = ? AND report_id = ?",
            [(task_id.data, r.data) for r in report_ids],
        )

    def count_client_reports_for_interval(self, task_id: TaskId, interval: Interval) -> int:
        return self._c.execute(
            "SELECT COUNT(*) FROM client_reports WHERE task_id = ? AND client_time >= ? AND client_time < ?",
            (task_id.data, interval.start.seconds, interval.end.seconds),
        ).fetchone()[0]

    def count_client_reports_for_task(self, task_id: TaskId) -> tuple[int, int]:
        """(total, aggregated) — powers the ops API task metrics
        (reference datastore.rs:1101 get_task_metrics)."""
        row = self._c.execute(
            "SELECT COUNT(*), COALESCE(SUM(aggregation_started), 0) FROM client_reports WHERE task_id = ?",
            (task_id.data,),
        ).fetchone()
        return row[0], row[1]

    # The durable tables the health sampler's periodic row-count tx
    # samples into janus_datastore_table_rows{table} — the flight
    # recorder's datastore_rows series (flat under load + GC is the
    # endurance gate). COUNT(*) per table in one read tx: cheap at the
    # row counts a healthy GC maintains, and the point is to notice
    # when they stop being cheap.
    COUNTED_TABLES = (
        "tasks",
        "client_reports",
        "aggregation_jobs",
        "report_aggregations",
        "batch_aggregations",
        "collection_jobs",
        "aggregate_share_jobs",
        "batches",
        "outstanding_batches",
        "task_counters",
    )

    def count_table_rows(self) -> dict[str, int]:
        """{table: row count} over COUNTED_TABLES."""
        return {
            t: self._c.execute(f"SELECT COUNT(*) FROM {t}").fetchone()[0]  # noqa: S608
            for t in self.COUNTED_TABLES
        }

    def delete_expired_client_reports(self, task_id: TaskId, cutoff: Time, limit: int) -> tuple[int, int]:
        """(never-claimed, claimed) expired rows deleted — split by
        aggregation_started so the GC can attribute expiry in the
        conservation ledger: a never-claimed report leaves the pending
        pool for the `expired` terminal, while a claimed one already
        resolved (or will resolve) through its report_aggregations row
        and only its storage is reclaimed here."""
        out = []
        for started in (0, 1):
            cur = self._c.execute(
                "DELETE FROM client_reports WHERE (task_id, report_id) IN ("
                " SELECT task_id, report_id FROM client_reports"
                " WHERE task_id = ? AND client_time < ? AND aggregation_started = ? LIMIT ?)",
                (task_id.data, cutoff.seconds, started, max(0, limit - sum(out))),
            )
            out.append(cur.rowcount)
        return out[0], out[1]

    # ---- report-flow conservation ledger (janus_tpu/ledger.py) ----
    def increment_task_counters(self, task_id: TaskId, deltas: dict[str, int]) -> None:
        """Upsert-add monotone lifecycle counters for a task. MUST be
        called inside the same transaction as the state change being
        counted: run_tx re-runs the whole closure on a retry, so an
        in-tx increment is exactly-once where an in-process counter
        would double-count (the documented run_tx retry discipline)."""
        rows = [(task_id.data, name, int(n)) for name, n in deltas.items() if n]
        if not rows:
            return
        self._c.executemany(
            "INSERT INTO task_counters (task_id, counter_name, amount) VALUES (?,?,?)"
            " ON CONFLICT (task_id, counter_name) DO UPDATE SET"
            " amount = task_counters.amount + excluded.amount",
            rows,
        )

    def get_task_counters(self, task_id: TaskId) -> dict[str, int]:
        rows = self._c.execute(
            "SELECT counter_name, amount FROM task_counters WHERE task_id = ?",
            (task_id.data,),
        ).fetchall()
        return {str(r[0]): int(r[1]) for r in rows}

    def get_all_task_counters(self) -> dict[bytes, dict[str, int]]:
        """{task_id: {counter: amount}} over every task with books."""
        out: dict[bytes, dict[str, int]] = {}
        for task_id, name, amount in self._c.execute(
            "SELECT task_id, counter_name, amount FROM task_counters"
        ).fetchall():
            out.setdefault(bytes(task_id), {})[str(name)] = int(amount)
        return out

    def ledger_inflight_by_task(self) -> dict[bytes, dict[str, int]]:
        """{task_id: {category: count}} of attributably in-flight
        reports, read in one transaction so the ledger's balance
        evaluates against a single snapshot:

        - pending_reports: admitted client_reports no aggregation job
          has claimed yet (aggregation_started = 0)
        - pending_aggregation: report_aggregations still in a
          non-terminal state (start / waiting_*) — claimed, outcome due
        - pending_aggregation_param: same, but for jobs carrying a
          non-empty aggregation parameter (the param-fanout lane —
          those rows debit `admitted_param`, never `admitted`)
        - awaiting_collection: aggregated report mass sitting in
          uncollected batch_aggregations shards
        """
        out: dict[bytes, dict[str, int]] = {}
        for task_id, n in self._c.execute(
            "SELECT task_id, COUNT(*) FROM client_reports"
            " WHERE aggregation_started = 0 GROUP BY task_id"
        ).fetchall():
            out.setdefault(bytes(task_id), {})["pending_reports"] = int(n)
        # only RAs of live jobs: abandon_job releases a job's START rows
        # back to the unclaimed pool without rewriting them, so counting
        # an abandoned job's rows would double-book those reports (and a
        # WAITING row stuck in an abandoned job SHOULD read as imbalance
        # — it will never resolve, which is exactly a lost report)
        for task_id, param, n in self._c.execute(
            "SELECT ra.task_id, aj.aggregation_parameter <> ?, COUNT(*)"
            " FROM report_aggregations ra"
            " JOIN aggregation_jobs aj"
            "   ON aj.task_id = ra.task_id AND aj.job_id = ra.job_id"
            " WHERE ra.state IN ('start', 'waiting_leader', 'waiting_helper')"
            " AND aj.state = 'in_progress'"
            " GROUP BY 1, 2",
            (b"",),
        ).fetchall():
            key = "pending_aggregation_param" if param else "pending_aggregation"
            t = out.setdefault(bytes(task_id), {})
            t[key] = t.get(key, 0) + int(n)
        for task_id, n in self._c.execute(
            "SELECT task_id, COALESCE(SUM(report_count), 0) FROM batch_aggregations"
            " WHERE state <> 'collected' GROUP BY task_id"
        ).fetchall():
            out.setdefault(bytes(task_id), {})["awaiting_collection"] = int(n)
        return out

    def ledger_batch_counts(self, task_id: TaskId) -> dict[str, int]:
        """{"<batch_identifier hex>:<aggregation_parameter hex>":
        aggregated report count} for a task — the cross-aggregator
        reconciliation payload (both aggregators persist
        batch_aggregations; equal per-key counts mean neither side
        silently dropped or double-counted a report the other
        aggregated — the observability analog of a linear tag). Keyed
        per (batch, param): a multi-parameter task accumulates the same
        batch once per collection parameter, and summing across params
        would inflate the helper's count against a leader comparison
        that covers a single collection's parameter."""
        rows = self._c.execute(
            "SELECT batch_identifier, aggregation_parameter,"
            " COALESCE(SUM(report_count), 0)"
            " FROM batch_aggregations WHERE task_id = ?"
            " GROUP BY batch_identifier, aggregation_parameter",
            (task_id.data,),
        ).fetchall()
        return {
            f"{bytes(r[0]).hex()}:{bytes(r[1]).hex()}": int(r[2]) for r in rows
        }

    def ledger_report_trace(self, task_id: TaskId, report_id: ReportId) -> dict:
        """One report's whereabouts across every pipeline table — the
        per-report drill-down behind tools/report_trace.py (the ledger
        says HOW MANY are unaccounted; this answers WHICH stage one
        specific report reached). Read-only; single snapshot."""
        out: dict = {"client_report": None, "report_aggregations": [], "batch_aggregations": []}
        row = self._c.execute(
            "SELECT client_time, aggregation_started FROM client_reports"
            " WHERE task_id = ? AND report_id = ?",
            (task_id.data, report_id.data),
        ).fetchone()
        client_time = None
        if row is not None:
            client_time = int(row[0])
            out["client_report"] = {
                "client_time": client_time,
                "aggregation_started": bool(row[1]),
            }
        for r in self._c.execute(
            "SELECT ra.job_id, ra.ord, ra.state, ra.prepare_error, ra.client_time,"
            " aj.state, aj.step, aj.lease_attempts"
            " FROM report_aggregations ra"
            " LEFT JOIN aggregation_jobs aj"
            "   ON aj.task_id = ra.task_id AND aj.job_id = ra.job_id"
            " WHERE ra.task_id = ? AND ra.report_id = ?"
            " ORDER BY ra.job_id, ra.ord",
            (task_id.data, report_id.data),
        ).fetchall():
            if client_time is None:
                client_time = int(r[4])
            out["report_aggregations"].append(
                {
                    "job_id": bytes(r[0]).hex(),
                    "ord": int(r[1]),
                    "state": str(r[2]),
                    "prepare_error": None if r[3] is None else int(r[3]),
                    "job_state": None if r[5] is None else str(r[5]),
                    "job_step": None if r[6] is None else int(r[6]),
                    "job_attempts": None if r[7] is None else int(r[7]),
                }
            )
        if client_time is not None:
            # every accumulator shard whose client interval covers this
            # report's timestamp — collected shards mean the report's
            # mass (if it FINISHED) has left through a collection
            for r in self._c.execute(
                "SELECT batch_identifier, ord, state, report_count"
                " FROM batch_aggregations WHERE task_id = ?"
                " AND client_interval_start <= ?"
                " AND client_interval_start + client_interval_duration > ?"
                " ORDER BY batch_identifier, ord",
                (task_id.data, client_time, client_time),
            ).fetchall():
                out["batch_aggregations"].append(
                    {
                        "batch_identifier": bytes(r[0]).hex(),
                        "ord": int(r[1]),
                        "state": str(r[2]),
                        "report_count": int(r[3]),
                    }
                )
        return out

    # ---- aggregation jobs (reference datastore.rs:1724-2051) ----
    def put_aggregation_job(self, job: AggregationJobModel) -> None:
        self._c.execute(
            "INSERT INTO aggregation_jobs (task_id, job_id, aggregation_parameter,"
            " partial_batch_identifier, client_interval_start, client_interval_duration,"
            " state, step, last_request_hash, trace_context, shard_key, lease_expiry)"
            " VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                job.task_id.data,
                job.job_id.data,
                job.aggregation_parameter,
                job.partial_batch_identifier,
                job.client_timestamp_interval.start.seconds,
                job.client_timestamp_interval.duration.seconds,
                job.state.value,
                job.step,
                job.last_request_hash,
                job.trace_context,
                job_shard_key(job.task_id.data, job.job_id.data),
                # eligible-since stamp: a past-expiry value means
                # "claimable"; the CREATION time (not 0) is what the
                # steal-after-delay fallback measures eligibility age
                # against — a fresh job must not look infinitely stale
                self._clock.now().seconds,
            ),
        )

    def get_aggregation_job(self, task_id: TaskId, job_id: AggregationJobId) -> AggregationJobModel | None:
        row = self._c.execute(
            "SELECT aggregation_parameter, partial_batch_identifier, client_interval_start,"
            " client_interval_duration, state, step, last_request_hash, trace_context"
            " FROM aggregation_jobs WHERE task_id = ? AND job_id = ?",
            (task_id.data, job_id.data),
        ).fetchone()
        if row is None:
            return None
        return AggregationJobModel(
            task_id,
            job_id,
            row[0],
            row[1],
            Interval(Time(row[2]), Duration(row[3])),
            AggregationJobState(row[4]),
            row[5],
            row[6],
            row[7],
        )

    def update_aggregation_job(self, job: AggregationJobModel) -> None:
        self._c.execute(
            "UPDATE aggregation_jobs SET state = ?, step = ?, last_request_hash = ?"
            " WHERE task_id = ? AND job_id = ?",
            (job.state.value, job.step, job.last_request_hash, job.task_id.data, job.job_id.data),
        )

    def get_aggregation_jobs_for_task(self, task_id: TaskId) -> list[AggregationJobModel]:
        rows = self._c.execute(
            "SELECT job_id FROM aggregation_jobs WHERE task_id = ? ORDER BY job_id",
            (task_id.data,),
        ).fetchall()
        return [self.get_aggregation_job(task_id, AggregationJobId(r[0])) for r in rows]

    def _acquire_jobs_batched(
        self,
        table: str,
        id_col: str,
        state_pred: str,
        lease_duration: Duration,
        limit: int,
        shard: ShardSpec | None,
        holder: bytes | None,
    ) -> list[tuple[bytes, bytes, bytes, int, int, int]]:
        """ONE claim transaction atomically leasing up to `limit` jobs
        (the reference's FOR UPDATE SKIP LOCKED queue-pop idiom,
        datastore.rs:1836, batched instead of per-row): a single
        UPDATE whose candidate subquery carries the eligibility window,
        the fleet shard predicate, and a RANDOMIZED claim order — every
        replica walking the same ORDER BY lease_expiry scan oldest-first
        maximized claim collisions, so candidates are ordered own-shard
        first, then random() within the eligible window.

        The randomization is WINDOWED: the inner candidate scan walks
        the (state, lease_expiry) index oldest-first into a bounded
        window (max(4*limit, 64) rows — never a whole-backlog sort),
        and the shuffle happens within that window. A deep post-outage
        backlog therefore still drains oldest-first at window
        granularity, and the per-claim sort cost is O(W log W) bounded
        regardless of eligible-set size.

        Shard predicate (docs/ARCHITECTURE.md "Running a fleet"):
        in-shard rows (persisted shard_key mod shard_count ==
        shard_index) are claimable the moment their lease expires;
        out-of-shard rows only after sitting eligible for steal_after_s
        — a dead replica's shard drains instead of starving, while live
        replicas stay off each other's rows.

        The whole batch shares one fresh token (row identity pins the
        guarded release; the token carries the claiming replica's
        provenance tag and the claim-generation randomness). Postgres
        takes the single-statement UPDATE .. IN (SELECT .. FOR UPDATE
        SKIP LOCKED) RETURNING form; pre-3.35 sqlite takes the
        two-statement form, exact inside the serialized transaction.

        Returns [(task_id, job_id, token, expiry, lease_attempts,
        shard_key)] — the STORED shard key rides along so the caller
        can tell a genuine steal (foreign shard_key >= 0) from a
        hand-back claim (shard_key < 0, affinity released)."""
        now = self._clock.now().seconds
        expiry = now + lease_duration.seconds
        token = make_lease_token(holder)
        eligible = f"{state_pred} AND lease_expiry <= ?"
        params: list = [now]
        order = "random()"
        if shard is not None and shard.active:
            count = int(shard.shard_count)
            index = int(shard.shard_index) % count
            # three ways past the shard fence: it's ours; its affinity
            # was RELEASED by a clean hand-back (shard_key < 0); or it
            # has sat eligible past the steal delay (dead holder)
            eligible = (
                f"{state_pred} AND lease_expiry <= ?"
                f" AND (shard_key % {count} = {index} OR shard_key < 0"
                " OR lease_expiry <= ?)"
            )
            params = [now, now - max(0, int(shard.steal_after_s))]
            order = (
                f"CASE WHEN shard_key % {count} = {index} THEN 0 ELSE 1 END,"
                " random()"
            )
        # inner: index-ordered oldest-first candidate WINDOW (bounded
        # sort, fairness); outer: own-shard-first randomized claim
        # order within it (collision avoidance); the PG form locks the
        # window rows FOR UPDATE SKIP LOCKED at the inner scan
        window = max(4 * int(limit), 64)
        select_sql = (
            f"SELECT task_id, {id_col} FROM ("
            f"SELECT task_id, {id_col}, shard_key FROM {table}"
            f" WHERE {eligible} ORDER BY lease_expiry LIMIT {window}"
            f"{self._lease_suffix}"
            f") AS cand ORDER BY {order} LIMIT ?"
        )
        set_sql = (
            f"UPDATE {table} SET lease_expiry = ?, lease_token = ?,"
            " lease_attempts = lease_attempts + 1"
        )
        if self._returning:
            rows = self._c.execute(
                set_sql
                + f" WHERE (task_id, {id_col}) IN ({select_sql})"
                + f" RETURNING task_id, {id_col}, lease_attempts, shard_key",
                (expiry, token, *params, limit),
            ).fetchall()
        else:
            cand = self._c.execute(select_sql, (*params, limit)).fetchall()
            if not cand:
                return []
            marks = ",".join(["(?,?)"] * len(cand))
            flat = [x for row in cand for x in row]
            self._c.execute(
                set_sql
                + f" WHERE (task_id, {id_col}) IN (VALUES {marks}) AND {eligible}",
                (expiry, token, *flat, *params),
            )
            # the fresh per-claim token identifies exactly this batch
            rows = self._c.execute(
                f"SELECT task_id, {id_col}, lease_attempts, shard_key FROM {table}"
                " WHERE lease_token = ?",
                (token,),
            ).fetchall()
        return [(t, j, token, expiry, att, sk) for t, j, att, sk in rows]

    def acquire_incomplete_aggregation_jobs(
        self,
        lease_duration: Duration,
        limit: int,
        shard: ShardSpec | None = None,
        holder: bytes | None = None,
    ) -> list[AcquiredAggregationJob]:
        """Batched lease claim (reference datastore.rs:1836; see
        _acquire_jobs_batched for the claim-tx/shard/steal contract)."""
        return [
            AcquiredAggregationJob(
                TaskId(t),
                AggregationJobId(j),
                Lease(token, Time(expiry), att),
                shard_key=sk,
            )
            for t, j, token, expiry, att, sk in self._acquire_jobs_batched(
                "aggregation_jobs",
                "job_id",
                "state = 'in_progress'",
                lease_duration,
                limit,
                shard,
                holder,
            )
        ]

    def _lease_conflict(self, kind: str, op: str, msg: str) -> LeaseConflict:
        """Count a token-mismatch on a guarded lease write
        (janus_lease_conflicts_total{kind,op}) and build the
        LeaseConflict to raise — a fleet losing claim races must be
        visible, never a silent no-op. Counted here, not in run_tx:
        LeaseConflict is classified fatal (deterministic), so the tx
        never retries and the event counts exactly once."""
        from .. import metrics

        metrics.lease_conflicts_total.add(
            kind=kind, op=op, **metrics.replica_labels()
        )
        return LeaseConflict(msg)

    def release_aggregation_job(self, acquired: AcquiredAggregationJob) -> None:
        """reference datastore.rs:1905; raises LeaseConflict (counted)
        if the lease was lost (expired + re-acquired elsewhere). The
        release stamps NOW (not 0) as the eligible-since so the
        steal-after fencing measures a real eligibility age, and
        RE-STAMPS the shard affinity (derivable from the row identity)
        so a row that crossed a restart via the hand-back sentinel
        rejoins its shard for the rest of its multi-step life."""
        cur = self._c.execute(
            "UPDATE aggregation_jobs SET lease_expiry = ?, lease_token = NULL,"
            " lease_attempts = 0, shard_key = ?"
            " WHERE task_id = ? AND job_id = ? AND lease_token = ?",
            (
                self._clock.now().seconds,
                job_shard_key(acquired.task_id.data, acquired.job_id.data),
                acquired.task_id.data,
                acquired.job_id.data,
                acquired.lease.token,
            ),
        )
        if cur.rowcount != 1:
            raise self._lease_conflict(
                "aggregation", "release", "lease token mismatch on release"
            )

    def step_back_aggregation_job(
        self,
        acquired: AcquiredAggregationJob,
        reacquire_delay_s: int = 0,
        count_attempt: bool = False,
        handback: bool = False,
    ) -> None:
        """Early lease release without resetting the attempt ledger (the
        difference from release_aggregation_job, whose lease_attempts=0
        is 'this step SUCCEEDED'): the job becomes reacquirable after
        `reacquire_delay_s` instead of aging out a full lease TTL.

        Used when the step could not run through no fault of the job —
        outbound circuit open to the helper (wait out the cooldown) or
        shutdown drain (handback=True: the row's shard AFFINITY is
        released, shard_key = HANDBACK_SHARD_KEY, so a surviving peer
        of ANY shard claims it immediately — a clean hand-back must
        not sit behind the steal fence meant for dead holders, and the
        claim is classifiable as a hand-back, never a steal).
        count_attempt=False refunds the acquire's lease_attempts
        increment so a helper outage cannot march jobs to abandonment;
        True keeps it counted (a genuinely failed step released
        early). Raises TxConflict if the lease was lost."""
        now = self._clock.now().seconds
        # CASE instead of MAX/GREATEST: scalar max() is sqlite-only and
        # GREATEST needs sqlite >= 3.44 / postgres — CASE runs on both
        attempts_sql = (
            "lease_attempts"
            if count_attempt
            else "CASE WHEN lease_attempts > 0 THEN lease_attempts - 1 ELSE 0 END"
        )
        # hand-back releases the shard affinity; every other step-back
        # re-stamps it (restoring a row that crossed a restart via the
        # sentinel to its shard)
        shard_key = (
            HANDBACK_SHARD_KEY
            if handback
            else job_shard_key(acquired.task_id.data, acquired.job_id.data)
        )
        cur = self._c.execute(
            "UPDATE aggregation_jobs SET lease_expiry = ?, lease_token = NULL,"
            f" lease_attempts = {attempts_sql}, shard_key = ?"
            " WHERE task_id = ? AND job_id = ? AND lease_token = ?",
            (
                now + max(0, int(reacquire_delay_s)),
                shard_key,
                acquired.task_id.data,
                acquired.job_id.data,
                acquired.lease.token,
            ),
        )
        if cur.rowcount != 1:
            raise self._lease_conflict(
                "aggregation", "step_back", "lease token mismatch on step-back"
            )

    # ---- report aggregations (reference datastore.rs:2052-2455) ----
    def put_report_aggregation(self, ra: ReportAggregationModel) -> None:
        row_key = ra.task_id.data + ra.job_id.data + ra.ord.to_bytes(8, "big")
        blob = (
            self._crypter.encrypt("report_aggregations", row_key, "prep_blob", ra.prep_blob)
            if ra.prep_blob
            else b""
        )
        self._c.execute(
            "INSERT INTO report_aggregations (task_id, job_id, report_id, client_time, ord,"
            " state, prep_blob, prepare_error) VALUES (?,?,?,?,?,?,?,?)",
            (
                ra.task_id.data,
                ra.job_id.data,
                ra.report_id.data,
                ra.client_time.seconds,
                ra.ord,
                ra.state.value,
                blob,
                int(ra.prepare_error) if ra.prepare_error is not None else None,
            ),
        )

    def update_report_aggregation(self, ra: ReportAggregationModel) -> None:
        row_key = ra.task_id.data + ra.job_id.data + ra.ord.to_bytes(8, "big")
        blob = (
            self._crypter.encrypt("report_aggregations", row_key, "prep_blob", ra.prep_blob)
            if ra.prep_blob
            else b""
        )
        self._c.execute(
            "UPDATE report_aggregations SET state = ?, prep_blob = ?, prepare_error = ?"
            " WHERE task_id = ? AND job_id = ? AND ord = ?",
            (
                ra.state.value,
                blob,
                int(ra.prepare_error) if ra.prepare_error is not None else None,
                ra.task_id.data,
                ra.job_id.data,
                ra.ord,
            ),
        )

    def get_report_aggregations_for_job(
        self, task_id: TaskId, job_id: AggregationJobId
    ) -> list[ReportAggregationModel]:
        rows = self._c.execute(
            "SELECT report_id, client_time, ord, state, prep_blob, prepare_error"
            " FROM report_aggregations WHERE task_id = ? AND job_id = ? ORDER BY ord",
            (task_id.data, job_id.data),
        ).fetchall()
        out = []
        for r in rows:
            row_key = task_id.data + job_id.data + r[2].to_bytes(8, "big")
            blob = (
                self._crypter.decrypt("report_aggregations", row_key, "prep_blob", r[4])
                if r[4]
                else b""
            )
            out.append(
                ReportAggregationModel(
                    task_id,
                    job_id,
                    ReportId(r[0]),
                    Time(r[1]),
                    r[2],
                    ReportAggregationState(r[3]),
                    blob,
                    PrepareError(r[5]) if r[5] is not None else None,
                )
            )
        return out

    def get_aggregated_report_ids_for_param(
        self, task_id: TaskId, report_ids: list[ReportId], aggregation_parameter: bytes
    ) -> set[bytes]:
        """Param-scoped replay check (VDAFs with nontrivial aggregation
        parameters, e.g. Poplar1): which of `report_ids` already have a
        report-aggregation row under a job with THIS parameter. A
        report legitimately aggregates once per parameter."""
        out: set[bytes] = set()
        ids = [r.data for r in report_ids]
        for lo in range(0, len(ids), 500):
            chunk = ids[lo : lo + 500]
            marks = ",".join("?" * len(chunk))
            rows = self._c.execute(
                "SELECT DISTINCT ra.report_id FROM report_aggregations ra"
                " JOIN aggregation_jobs aj ON aj.task_id = ra.task_id"
                "  AND aj.job_id = ra.job_id"
                " WHERE ra.task_id = ? AND aj.aggregation_parameter = ?"
                f" AND ra.report_id IN ({marks})",
                (task_id.data, aggregation_parameter, *chunk),
            ).fetchall()
            out.update(r[0] for r in rows)
        return out

    def get_client_report_ids_in_interval(
        self, task_id: TaskId, interval: Interval
    ) -> list[tuple[ReportId, Time]]:
        """All stored client reports whose time falls in the interval
        (collection-driven aggregation for parameterized VDAFs)."""
        rows = self._c.execute(
            "SELECT report_id, client_time FROM client_reports"
            " WHERE task_id = ? AND client_time >= ? AND client_time < ?"
            " ORDER BY client_time, report_id",
            (task_id.data, interval.start.seconds, interval.end.seconds),
        ).fetchall()
        return [(ReportId(r[0]), Time(r[1])) for r in rows]

    def count_active_aggregation_jobs_for_param(
        self, task_id: TaskId, aggregation_parameter: bytes
    ) -> int:
        return self._c.execute(
            "SELECT COUNT(*) FROM aggregation_jobs"
            " WHERE task_id = ? AND aggregation_parameter = ? AND state = 'in_progress'",
            (task_id.data, aggregation_parameter),
        ).fetchone()[0]

    def get_aggregated_report_ids(self, task_id: TaskId, report_ids: list[ReportId]) -> set[bytes]:
        """Which of `report_ids` already have ANY report-aggregation row
        (helper replay check) — one set query for the whole init batch,
        not a per-report loop (the reference's single
        get_unaggregated-style set op; was VERDICT r2 Weak #2)."""
        out: set[bytes] = set()
        ids = [r.data for r in report_ids]
        # SQLite caps host parameters (default 999); chunk well under it
        for lo in range(0, len(ids), 500):
            chunk = ids[lo : lo + 500]
            marks = ",".join("?" * len(chunk))
            rows = self._c.execute(
                "SELECT DISTINCT report_id FROM report_aggregations"
                f" WHERE task_id = ? AND report_id IN ({marks})",
                (task_id.data, *chunk),
            ).fetchall()
            out.update(r[0] for r in rows)
        return out

    # ---- batch aggregations (reference datastore.rs:3020-3368) ----
    def put_batch_aggregation(self, ba: BatchAggregation) -> None:
        try:
            self._c.execute(
                "INSERT INTO batch_aggregations (task_id, batch_identifier, aggregation_parameter,"
                " ord, state, aggregate_share, report_count, client_interval_start,"
                " client_interval_duration, checksum) VALUES (?,?,?,?,?,?,?,?,?,?)",
                (
                    ba.task_id.data,
                    ba.batch_identifier,
                    ba.aggregation_parameter,
                    ba.ord,
                    ba.state.value,
                    ba.aggregate_share,
                    ba.report_count,
                    ba.client_timestamp_interval.start.seconds,
                    ba.client_timestamp_interval.duration.seconds,
                    ba.checksum.data,
                ),
            )
        except _INTEGRITY_ERRORS as e:
            # unique violation -> retryable conflict (reference accumulator.rs:173-199)
            raise TxConflict(str(e)) from e

    def update_batch_aggregation(self, ba: BatchAggregation) -> None:
        self._c.execute(
            "UPDATE batch_aggregations SET state = ?, aggregate_share = ?, report_count = ?,"
            " client_interval_start = ?, client_interval_duration = ?, checksum = ?"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ? AND ord = ?",
            (
                ba.state.value,
                ba.aggregate_share,
                ba.report_count,
                ba.client_timestamp_interval.start.seconds,
                ba.client_timestamp_interval.duration.seconds,
                ba.checksum.data,
                ba.task_id.data,
                ba.batch_identifier,
                ba.aggregation_parameter,
                ba.ord,
            ),
        )

    def get_batch_aggregation(
        self, task_id: TaskId, batch_identifier: bytes, agg_param: bytes, ord: int
    ) -> BatchAggregation | None:
        row = self._c.execute(
            "SELECT state, aggregate_share, report_count, client_interval_start,"
            " client_interval_duration, checksum FROM batch_aggregations"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ? AND ord = ?",
            (task_id.data, batch_identifier, agg_param, ord),
        ).fetchone()
        if row is None:
            return None
        return BatchAggregation(
            task_id,
            batch_identifier,
            agg_param,
            ord,
            BatchAggregationState(row[0]),
            row[1],
            row[2],
            Interval(Time(row[3]), Duration(row[4])),
            ReportIdChecksum(row[5]),
        )

    def sum_batch_aggregation_report_count(
        self, task_id: TaskId, batch_identifier: bytes, param: bytes
    ) -> int:
        """Aggregated report total for a batch, one SELECT across shards."""
        row = self._c.execute(
            "SELECT COALESCE(SUM(report_count), 0) FROM batch_aggregations"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ?",
            (task_id.data, batch_identifier, param),
        ).fetchone()
        return int(row[0])

    def batch_has_collected_shard(
        self, task_id: TaskId, batch_identifier: bytes, param: bytes
    ) -> bool:
        """Cheap existence check: is any shard of this batch collected?"""
        row = self._c.execute(
            "SELECT 1 FROM batch_aggregations WHERE task_id = ? AND batch_identifier = ?"
            " AND aggregation_parameter = ? AND state = 'collected' LIMIT 1",
            (task_id.data, batch_identifier, param),
        ).fetchone()
        return row is not None

    def get_batch_aggregations_for_batch(
        self, task_id: TaskId, batch_identifier: bytes, agg_param: bytes
    ) -> list[BatchAggregation]:
        rows = self._c.execute(
            "SELECT ord FROM batch_aggregations WHERE task_id = ? AND batch_identifier = ?"
            " AND aggregation_parameter = ? ORDER BY ord",
            (task_id.data, batch_identifier, agg_param),
        ).fetchall()
        return [
            self.get_batch_aggregation(task_id, batch_identifier, agg_param, r[0]) for r in rows
        ]

    def get_batch_aggregations_intersecting_interval(
        self, task_id: TaskId, interval: Interval, aggregation_parameter: bytes | None = None
    ) -> list[BatchAggregation]:
        """Time-interval collection: find shard rows whose batch interval
        falls inside the collection interval (reference
        query_type.rs:204 CollectableQueryType).

        aggregation_parameter: restrict to rows accumulated under that
        parameter (parameterized VDAFs aggregate the same interval once
        per parameter); None matches every parameter."""
        rows = self._c.execute(
            "SELECT DISTINCT batch_identifier, aggregation_parameter FROM batch_aggregations"
            " WHERE task_id = ?",
            (task_id.data,),
        ).fetchall()
        out = []
        for bid, param in rows:
            if aggregation_parameter is not None and param != aggregation_parameter:
                continue
            biv = Interval.from_bytes(bid)
            if biv.start >= interval.start and biv.end <= interval.end:
                out.extend(self.get_batch_aggregations_for_batch(task_id, bid, param))
        return out

    def mark_batch_aggregations_collected(
        self, task_id: TaskId, batch_identifier: bytes, agg_param: bytes
    ) -> None:
        self._c.execute(
            "UPDATE batch_aggregations SET state = 'collected'"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ?",
            (task_id.data, batch_identifier, agg_param),
        )

    def delete_expired_batch_aggregations(self, task_id: TaskId, cutoff: Time, limit: int) -> int:
        cur = self._c.execute(
            "DELETE FROM batch_aggregations WHERE (task_id, batch_identifier, aggregation_parameter, ord) IN ("
            " SELECT task_id, batch_identifier, aggregation_parameter, ord FROM batch_aggregations"
            " WHERE task_id = ? AND client_interval_start + client_interval_duration < ? LIMIT ?)",
            (task_id.data, cutoff.seconds, limit),
        )
        return cur.rowcount

    # ---- collection jobs (reference datastore.rs:2456-3019) ----
    def put_collection_job(self, job: CollectionJobModel) -> None:
        self._c.execute(
            "INSERT INTO collection_jobs (task_id, collection_job_id, query, aggregation_parameter,"
            " batch_identifier, state, trace_context, shard_key, lease_expiry)"
            " VALUES (?,?,?,?,?,?,?,?,?)",
            (
                job.task_id.data,
                job.collection_job_id.data,
                job.query,
                job.aggregation_parameter,
                job.batch_identifier,
                job.state.value,
                job.trace_context,
                job_shard_key(job.task_id.data, job.collection_job_id.data),
                self._clock.now().seconds,  # eligible-since (see agg jobs)
            ),
        )

    def get_collection_job(
        self, task_id: TaskId, collection_job_id: CollectionJobId
    ) -> CollectionJobModel | None:
        row = self._c.execute(
            "SELECT query, aggregation_parameter, batch_identifier, state, report_count,"
            " client_interval_start, client_interval_duration, leader_aggregate_share,"
            " helper_encrypted_aggregate_share, trace_context FROM collection_jobs"
            " WHERE task_id = ? AND collection_job_id = ?",
            (task_id.data, collection_job_id.data),
        ).fetchone()
        if row is None:
            return None
        row_key = task_id.data + collection_job_id.data
        las = (
            self._crypter.decrypt("collection_jobs", row_key, "leader_aggregate_share", row[7])
            if row[7]
            else None
        )
        return CollectionJobModel(
            task_id,
            collection_job_id,
            row[0],
            row[1],
            row[2],
            CollectionJobState(row[3]),
            row[4],
            Interval(Time(row[5]), Duration(row[6])) if row[5] is not None else None,
            las,
            row[8],
            row[9],
        )

    def get_collection_job_batches_for_task(self, task_id: TaskId) -> list[tuple[bytes, bytes, str]]:
        """[(batch_identifier, query, state)] over every collection job
        of the task — feeds the leader's time-interval overlap scan
        (reference query_type.rs:204)."""
        rows = self._c.execute(
            "SELECT batch_identifier, query, state FROM collection_jobs WHERE task_id = ?",
            (task_id.data,),
        ).fetchall()
        return [(r[0], r[1], r[2]) for r in rows]

    def count_collection_jobs_for_batch(self, task_id: TaskId, batch_identifier: bytes) -> int:
        """Queries consumed against a batch (leader-side
        max_batch_query_count; deleted jobs still count — the budget is
        spent)."""
        return self._c.execute(
            "SELECT COUNT(*) FROM collection_jobs WHERE task_id = ? AND batch_identifier = ?",
            (task_id.data, batch_identifier),
        ).fetchone()[0]

    def find_collection_job_by_query(
        self, task_id: TaskId, query: bytes, aggregation_parameter: bytes = b""
    ) -> CollectionJobModel | None:
        """Idempotent collection-job creation (reference
        aggregator.rs:2233). Collection identity is (query, agg param):
        distinct aggregation parameters over the same query are
        distinct collections (each consuming batch query count)."""
        row = self._c.execute(
            "SELECT collection_job_id FROM collection_jobs"
            " WHERE task_id = ? AND query = ? AND aggregation_parameter = ?",
            (task_id.data, query, aggregation_parameter),
        ).fetchone()
        return self.get_collection_job(task_id, CollectionJobId(row[0])) if row else None

    def update_collection_job(self, job: CollectionJobModel) -> None:
        row_key = job.task_id.data + job.collection_job_id.data
        las = (
            self._crypter.encrypt(
                "collection_jobs", row_key, "leader_aggregate_share", job.leader_aggregate_share
            )
            if job.leader_aggregate_share
            else None
        )
        self._c.execute(
            "UPDATE collection_jobs SET state = ?, report_count = ?, client_interval_start = ?,"
            " client_interval_duration = ?, leader_aggregate_share = ?, helper_encrypted_aggregate_share = ?"
            " WHERE task_id = ? AND collection_job_id = ?",
            (
                job.state.value,
                job.report_count,
                job.client_timestamp_interval.start.seconds if job.client_timestamp_interval else None,
                job.client_timestamp_interval.duration.seconds if job.client_timestamp_interval else None,
                las,
                job.helper_encrypted_aggregate_share,
                job.task_id.data,
                job.collection_job_id.data,
            ),
        )

    def acquire_incomplete_collection_jobs(
        self,
        lease_duration: Duration,
        limit: int,
        shard: ShardSpec | None = None,
        holder: bytes | None = None,
    ) -> list[AcquiredCollectionJob]:
        """reference datastore.rs:2853; batched claim tx — see
        _acquire_jobs_batched for the claim-tx/shard/steal contract."""
        return [
            AcquiredCollectionJob(
                TaskId(t),
                CollectionJobId(j),
                Lease(token, Time(expiry), att),
                shard_key=sk,
            )
            for t, j, token, expiry, att, sk in self._acquire_jobs_batched(
                "collection_jobs",
                "collection_job_id",
                "state IN ('start', 'collectable')",
                lease_duration,
                limit,
                shard,
                holder,
            )
        ]

    def release_collection_job(self, acquired: AcquiredCollectionJob) -> None:
        cur = self._c.execute(
            "UPDATE collection_jobs SET lease_expiry = ?, lease_token = NULL,"
            " lease_attempts = 0, shard_key = ?"
            " WHERE task_id = ? AND collection_job_id = ? AND lease_token = ?",
            (
                self._clock.now().seconds,  # eligible-since (see agg jobs)
                # re-stamp affinity (see release_aggregation_job)
                job_shard_key(
                    acquired.task_id.data, acquired.collection_job_id.data
                ),
                acquired.task_id.data,
                acquired.collection_job_id.data,
                acquired.lease.token,
            ),
        )
        if cur.rowcount != 1:
            raise self._lease_conflict(
                "collection", "release", "lease token mismatch on release"
            )

    def step_back_collection_job(
        self,
        acquired: AcquiredCollectionJob,
        reacquire_delay_s: int = 0,
        count_attempt: bool = False,
        handback: bool = False,
    ) -> None:
        """Collection-job analog of step_back_aggregation_job (early
        release with a reacquire delay, attempts preserved/refunded;
        handback releases the row's shard affinity past any steal
        fence)."""
        now = self._clock.now().seconds
        # CASE instead of MAX/GREATEST: scalar max() is sqlite-only and
        # GREATEST needs sqlite >= 3.44 / postgres — CASE runs on both
        attempts_sql = (
            "lease_attempts"
            if count_attempt
            else "CASE WHEN lease_attempts > 0 THEN lease_attempts - 1 ELSE 0 END"
        )
        shard_key = (
            HANDBACK_SHARD_KEY
            if handback
            else job_shard_key(
                acquired.task_id.data, acquired.collection_job_id.data
            )
        )
        cur = self._c.execute(
            "UPDATE collection_jobs SET lease_expiry = ?, lease_token = NULL,"
            f" lease_attempts = {attempts_sql}, shard_key = ?"
            " WHERE task_id = ? AND collection_job_id = ? AND lease_token = ?",
            (
                now + max(0, int(reacquire_delay_s)),
                shard_key,
                acquired.task_id.data,
                acquired.collection_job_id.data,
                acquired.lease.token,
            ),
        )
        if cur.rowcount != 1:
            raise self._lease_conflict(
                "collection", "step_back", "lease token mismatch on step-back"
            )

    # ---- aggregate share jobs (reference datastore.rs:3369-3706) ----
    def put_aggregate_share_job(self, job: AggregateShareJob) -> None:
        row_key = job.task_id.data + job.batch_identifier
        share = self._crypter.encrypt(
            "aggregate_share_jobs", row_key, "helper_aggregate_share", job.helper_aggregate_share
        )
        self._c.execute(
            "INSERT INTO aggregate_share_jobs (task_id, batch_identifier, aggregation_parameter,"
            " helper_aggregate_share, report_count, checksum) VALUES (?,?,?,?,?,?)",
            (
                job.task_id.data,
                job.batch_identifier,
                job.aggregation_parameter,
                share,
                job.report_count,
                job.checksum.data,
            ),
        )

    def get_aggregate_share_job(
        self, task_id: TaskId, batch_identifier: bytes, agg_param: bytes
    ) -> AggregateShareJob | None:
        row = self._c.execute(
            "SELECT helper_aggregate_share, report_count, checksum FROM aggregate_share_jobs"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ?",
            (task_id.data, batch_identifier, agg_param),
        ).fetchone()
        if row is None:
            return None
        row_key = task_id.data + batch_identifier
        return AggregateShareJob(
            task_id,
            batch_identifier,
            agg_param,
            self._crypter.decrypt("aggregate_share_jobs", row_key, "helper_aggregate_share", row[0]),
            row[1],
            ReportIdChecksum(row[2]),
        )

    def count_aggregate_share_jobs_for_batch(self, task_id: TaskId, batch_identifier: bytes) -> int:
        return self._c.execute(
            "SELECT COUNT(*) FROM aggregate_share_jobs WHERE task_id = ? AND batch_identifier = ?",
            (task_id.data, batch_identifier),
        ).fetchone()[0]

    # ---- batches (reference datastore.rs:3944-4161) ----
    def put_batch(self, batch: Batch) -> None:
        self._c.execute(
            "INSERT INTO batches (task_id, batch_identifier, aggregation_parameter, state,"
            " outstanding_aggregation_jobs, client_interval_start, client_interval_duration)"
            " VALUES (?,?,?,?,?,?,?)",
            (
                batch.task_id.data,
                batch.batch_identifier,
                batch.aggregation_parameter,
                batch.state.value,
                batch.outstanding_aggregation_jobs,
                batch.client_timestamp_interval.start.seconds,
                batch.client_timestamp_interval.duration.seconds,
            ),
        )

    def get_batch(
        self, task_id: TaskId, batch_identifier: bytes, agg_param: bytes
    ) -> Batch | None:
        row = self._c.execute(
            "SELECT state, outstanding_aggregation_jobs, client_interval_start,"
            " client_interval_duration FROM batches"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ?",
            (task_id.data, batch_identifier, agg_param),
        ).fetchone()
        if row is None:
            return None
        return Batch(
            task_id,
            batch_identifier,
            agg_param,
            BatchState(row[0]),
            row[1],
            Interval(Time(row[2]), Duration(row[3])),
        )

    def update_batch(self, batch: Batch) -> None:
        self._c.execute(
            "UPDATE batches SET state = ?, outstanding_aggregation_jobs = ?,"
            " client_interval_start = ?, client_interval_duration = ?"
            " WHERE task_id = ? AND batch_identifier = ? AND aggregation_parameter = ?",
            (
                batch.state.value,
                batch.outstanding_aggregation_jobs,
                batch.client_timestamp_interval.start.seconds,
                batch.client_timestamp_interval.duration.seconds,
                batch.task_id.data,
                batch.batch_identifier,
                batch.aggregation_parameter,
            ),
        )

    # ---- outstanding batches (reference datastore.rs:3707-3943) ----
    def put_outstanding_batch(self, ob: OutstandingBatch) -> None:
        self._c.execute(
            "INSERT INTO outstanding_batches (task_id, batch_id, time_bucket_start, size)"
            " VALUES (?,?,?,?)",
            (
                ob.task_id.data,
                ob.batch_id.data,
                ob.time_bucket_start.seconds if ob.time_bucket_start else None,
                ob.size,
            ),
        )

    def get_outstanding_batches(
        self,
        task_id: TaskId,
        time_bucket_start: Time | None = None,
        include_filled: bool = False,
    ) -> list[OutstandingBatch]:
        # fullest-first: the reference's per-bucket priority queue
        # (batch_creator.rs:83) tops up the most-filled batch first; a
        # current-batch collection wants filled batches too (fullest wins)
        filled_clause = "" if include_filled else " AND filled = 0"
        if time_bucket_start is None:
            rows = self._c.execute(
                "SELECT batch_id, time_bucket_start, size FROM outstanding_batches"
                f" WHERE task_id = ?{filled_clause} ORDER BY size DESC",
                (task_id.data,),
            ).fetchall()
        else:
            rows = self._c.execute(
                "SELECT batch_id, time_bucket_start, size FROM outstanding_batches"
                f" WHERE task_id = ?{filled_clause} AND time_bucket_start = ?"
                " ORDER BY size DESC",
                (task_id.data, time_bucket_start.seconds),
            ).fetchall()
        return [
            OutstandingBatch(
                task_id, BatchId(r[0]), Time(r[1]) if r[1] is not None else None, r[2]
            )
            for r in rows
        ]

    def add_to_outstanding_batch(self, task_id: TaskId, batch_id: BatchId, n: int) -> int:
        """Record n more reports assigned to the batch; returns new size."""
        row = self._update_returning_one(
            "UPDATE outstanding_batches SET size = size + ? WHERE task_id = ? AND batch_id = ?",
            (n, task_id.data, batch_id.data),
            "size",
            "SELECT size FROM outstanding_batches WHERE task_id = ? AND batch_id = ?",
            (task_id.data, batch_id.data),
        )
        if row is None:
            raise TxConflict("outstanding batch vanished")
        return row[0]

    def mark_outstanding_batch_filled(self, task_id: TaskId, batch_id: BatchId) -> None:
        self._c.execute(
            "UPDATE outstanding_batches SET filled = 1 WHERE task_id = ? AND batch_id = ?",
            (task_id.data, batch_id.data),
        )

    def delete_outstanding_batch(self, task_id: TaskId, batch_id: BatchId) -> None:
        """Consume a batch chosen by a current-batch collection (reference
        delete_outstanding_batch, datastore.rs:3707-3943)."""
        self._c.execute(
            "DELETE FROM outstanding_batches WHERE task_id = ? AND batch_id = ?",
            (task_id.data, batch_id.data),
        )

    # ---- global HPKE keys (reference datastore.rs:4316-4435) ----
    def put_global_hpke_keypair(self, keypair, state: str = "pending") -> None:
        row_key = bytes([keypair.config.id.id])
        enc = self._crypter.encrypt("global_hpke_keys", row_key, "private_key", keypair.private_key)
        self._c.execute(
            "INSERT INTO global_hpke_keys (config_id, config, private_key, state, updated_at)"
            " VALUES (?,?,?,?,?)",
            (keypair.config.id.id, keypair.config.to_bytes(), enc, state, self._clock.now().seconds),
        )

    def get_global_hpke_keypairs(self) -> list[tuple]:
        """[(HpkeKeypair, state)] — import deferred to avoid cycles."""
        from ..core.hpke import HpkeKeypair
        from ..messages import HpkeConfig

        out = []
        for cid, cfg, sk, state in self._c.execute(
            "SELECT config_id, config, private_key, state FROM global_hpke_keys"
        ):
            row_key = bytes([cid])
            out.append(
                (
                    HpkeKeypair(
                        HpkeConfig.from_bytes(cfg),
                        self._crypter.decrypt("global_hpke_keys", row_key, "private_key", sk),
                    ),
                    state,
                )
            )
        return out

    def set_global_hpke_keypair_state(self, config_id: int, state: str) -> None:
        self._c.execute(
            "UPDATE global_hpke_keys SET state = ?, updated_at = ? WHERE config_id = ?",
            (state, self._clock.now().seconds, config_id),
        )

    def delete_global_hpke_keypair(self, config_id: int) -> None:
        self._c.execute("DELETE FROM global_hpke_keys WHERE config_id = ?", (config_id,))

    # ---- health/introspection reads (aggregator/health_sampler.py;
    # cheap aggregate queries only — the sampler runs them on a period
    # against the serving database) ----
    def count_jobs_by_state(self) -> dict[tuple[str, str], int]:
        """{(job type, state): count} over aggregation + collection jobs
        (the janus_jobs{type,state} backlog gauges)."""
        out: dict[tuple[str, str], int] = {}
        for typ, table in (
            ("aggregation", "aggregation_jobs"),
            ("collection", "collection_jobs"),
        ):
            for state, n in self._c.execute(
                f"SELECT state, COUNT(*) FROM {table} GROUP BY state"
            ).fetchall():
                out[(typ, str(state))] = int(n)
        return out

    def get_held_lease_expiries(self) -> list[tuple[str, bytes, bytes, int]]:
        """[(job type, task_id, job_id, lease_expiry)] for every lease
        currently outstanding (token set, not yet expired). The sampler
        tracks first-observation time per lease to export
        janus_job_lease_age_seconds. A projection of get_lease_holders
        — ONE definition of "held" for both reads."""
        return [
            (typ, task_id, job_id, expiry)
            for typ, task_id, job_id, _holder, expiry in self.get_lease_holders()
        ]

    def get_lease_holders(self) -> list[tuple[str, bytes, bytes, str, int]]:
        """[(job type, task_id, job_id, holder provenance hex,
        lease_expiry)] for every outstanding lease — which REPLICA
        holds which job, read off the provenance half of the lease
        token (docs/ARCHITECTURE.md "Running a fleet"). The fleet
        chaos scenario's who-holds-what assertions read it, and
        get_held_lease_expiries (the sampler's lease-age feed) is a
        projection of it."""
        now = self._clock.now().seconds
        out: list[tuple[str, bytes, bytes, str, int]] = []
        for typ, table, id_col in (
            ("aggregation", "aggregation_jobs", "job_id"),
            ("collection", "collection_jobs", "collection_job_id"),
        ):
            rows = self._c.execute(
                f"SELECT task_id, {id_col}, lease_token, lease_expiry FROM {table}"
                " WHERE lease_token IS NOT NULL AND lease_expiry > ?",
                (now,),
            ).fetchall()
            out.extend(
                (typ, r[0], r[1], lease_holder_hex(r[2]), int(r[3])) for r in rows
            )
        return out

    def min_unaggregated_report_time_by_task(self) -> list[tuple[bytes, int]]:
        """[(task_id, oldest unaggregated client_time)] — the
        aggregation-lag signal (oldest report no aggregation job has
        claimed yet); uses the client_reports_unaggregated partial
        index."""
        rows = self._c.execute(
            "SELECT task_id, MIN(client_time) FROM client_reports"
            " WHERE aggregation_started = 0 GROUP BY task_id"
        ).fetchall()
        return [(r[0], int(r[1])) for r in rows]

    def get_pending_aggregation_job_sizes(self, limit: int = 256) -> dict[bytes, list[int]]:
        """{task_id: [report counts]} of in-progress aggregation jobs —
        the batch geometry the NEXT driver pass will actually dispatch.
        Boot-time engine warmup reads this so it compiles the buckets
        real jobs need instead of blindly warming the minimum bucket
        (docs/ARCHITECTURE.md "Cold-start and prewarm")."""
        rows = self._c.execute(
            "SELECT aj.task_id, COUNT(*) FROM aggregation_jobs aj"
            " JOIN report_aggregations ra"
            "   ON ra.task_id = aj.task_id AND ra.job_id = aj.job_id"
            " WHERE aj.state = 'in_progress'"
            " GROUP BY aj.task_id, aj.job_id LIMIT ?",
            (int(limit),),
        ).fetchall()
        out: dict[bytes, list[int]] = {}
        for task_id, n in rows:
            out.setdefault(task_id, []).append(int(n))
        return out

    def count_batches_pending_collection(self) -> int:
        """Collection jobs still awaiting an aggregate result."""
        return int(
            self._c.execute(
                "SELECT COUNT(*) FROM collection_jobs"
                " WHERE state IN ('start', 'collectable')"
            ).fetchone()[0]
        )

    def unaggregated_report_time_quantiles_by_task(
        self, quantiles: tuple[float, ...] = (0.5, 0.95, 0.99), bucket_s: int = 60
    ) -> list[tuple[bytes, int, int, dict[float, int]]]:
        """[(task_id, count, exact oldest client_time, {q: client_time
        at the q age-quantile})] over unaggregated reports — the
        freshness DISTRIBUTION (plus the exact min, so the sampler
        feeds the oldest-age gauge and the quantile gauges from ONE
        scan instead of walking the partial index twice per tick).

        One index-only scan, aggregated DB-side into `bucket_s`-wide
        client_time buckets (integer division truncates identically on
        both engines), so a million-report backlog transfers a few
        hundred rows and the sampler never does per-quantile OFFSET
        walks. Quantiles come from the histogram: the q age-quantile is
        the bucket holding the report at 1-based rank n - ceil(q*(n-1))
        counting from the OLDEST, reported as that bucket's older edge
        — both choices bias toward the older report, the conservative
        direction for an SLO gauge. bucket_s bounds the resolution
        error (default one minute, far below any meaningful
        aggregation-lag alert threshold)."""
        import math

        rows = self._c.execute(
            "SELECT task_id, client_time / ?, COUNT(*), MIN(client_time)"
            " FROM client_reports"
            " WHERE aggregation_started = 0 GROUP BY task_id, client_time / ?"
            " ORDER BY task_id, client_time / ?",
            (bucket_s, bucket_s, bucket_s),
        ).fetchall()
        by_task: dict[bytes, list[tuple[int, int, int]]] = {}
        for task_id, bucket, cnt, bucket_min in rows:
            by_task.setdefault(task_id, []).append(
                (int(bucket), int(cnt), int(bucket_min))
            )
        out: list[tuple[bytes, int, int, dict[float, int]]] = []
        for task_id, buckets in by_task.items():
            n = sum(c for _, c, _ in buckets)
            oldest = buckets[0][2]  # ascending: first bucket holds the min
            vals: dict[float, int] = {}
            for q in quantiles:
                rank = n - math.ceil(q * (n - 1))
                cum = 0
                for bucket, cnt, _ in buckets:  # ascending time = oldest first
                    cum += cnt
                    if cum >= rank:
                        vals[q] = bucket * bucket_s
                        break
            out.append((task_id, n, oldest, vals))
        return out

    def get_aggregation_job_trace_contexts(
        self,
        task_id: TaskId,
        interval: Interval | None = None,
        partial_batch_identifier: bytes | None = None,
        limit: int = 64,
    ) -> list[str]:
        """Distinct persisted trace contexts of the aggregation jobs a
        collection covers (time-interval INTERSECTION — the same
        semantics as the batch gather, so a job whose claimed reports
        straddle the collection boundary still links — or fixed-size
        partial-batch-selector match) — the collection span's causality
        links back to the aggregation work that filled the batch.
        Callers wanting to detect truncation ask for one more than they
        display."""
        if interval is not None:
            rows = self._c.execute(
                "SELECT DISTINCT trace_context FROM aggregation_jobs"
                " WHERE task_id = ? AND trace_context IS NOT NULL"
                " AND client_interval_start < ?"
                " AND client_interval_start + client_interval_duration > ?"
                " LIMIT ?",
                (task_id.data, interval.end.seconds, interval.start.seconds, limit),
            ).fetchall()
        elif partial_batch_identifier is not None:
            rows = self._c.execute(
                "SELECT DISTINCT trace_context FROM aggregation_jobs"
                " WHERE task_id = ? AND trace_context IS NOT NULL"
                " AND partial_batch_identifier = ? LIMIT ?",
                (task_id.data, partial_batch_identifier, limit),
            ).fetchall()
        else:
            return []
        return [str(r[0]) for r in rows]

    # ---- GC (reference datastore.rs:4162-4315) ----
    def delete_expired_aggregation_artifacts(self, task_id: TaskId, cutoff: Time, limit: int) -> tuple[int, int, int]:
        """(jobs deleted, never-resolved rows of the canonical lane,
        never-resolved rows of the param-fanout lane). The row counts
        are the GC's ledger attribution: a non-terminal row deleted
        here would otherwise sit unaccounted forever (its job expired
        before resolving), so the GC books it `expired` /
        `expired_param` in the same transaction.

        Abandoned jobs need care: abandon_job returns a canonical job's
        START rows to the unclaimed pool (mark_reports_unaggregated),
        so those reports reach a real terminal later (re-aggregated,
        rejected, or expired as unclaimed client_reports) — booking the
        stale START rows again here would double-debit `admitted` and
        latch a false negative residual. Only an abandoned canonical
        job's waiting_* rows really are lost. Param-fanout jobs have no
        pool to return to (the per-param replay check treats ANY row as
        done), so ALL their non-terminal rows are lost on abandonment
        and book `expired_param` here."""
        rows = self._c.execute(
            "SELECT job_id, state, aggregation_parameter FROM aggregation_jobs"
            " WHERE task_id = ?"
            " AND client_interval_start + client_interval_duration < ? LIMIT ?",
            (task_id.data, cutoff.seconds, limit),
        ).fetchall()
        n = pending = pending_param = 0
        for job_id, job_state, agg_param in rows:
            is_param = bytes(agg_param or b"") != b""
            if str(job_state) == "abandoned" and not is_param:
                states = "('waiting_leader', 'waiting_helper')"
            else:
                states = "('start', 'waiting_leader', 'waiting_helper')"
            lost = int(
                self._c.execute(
                    "SELECT COUNT(*) FROM report_aggregations"
                    " WHERE task_id = ? AND job_id = ?"
                    f" AND state IN {states}",
                    (task_id.data, job_id),
                ).fetchone()[0]
            )
            if is_param:
                pending_param += lost
            else:
                pending += lost
            self._c.execute(
                "DELETE FROM report_aggregations WHERE task_id = ? AND job_id = ?",
                (task_id.data, job_id),
            )
            cur = self._c.execute(
                "DELETE FROM aggregation_jobs WHERE task_id = ? AND job_id = ?",
                (task_id.data, job_id),
            )
            n += cur.rowcount
        return n, pending, pending_param

    def delete_expired_collection_artifacts(self, task_id: TaskId, cutoff: Time, limit: int) -> int:
        # aggregate_share_jobs carry no client-time column in this schema;
        # they are removed with the task (delete_task), matching the row
        # budget the reference applies per GC pass.
        return self._c.execute(
            "DELETE FROM collection_jobs WHERE (task_id, collection_job_id) IN ("
            " SELECT task_id, collection_job_id FROM collection_jobs"
            " WHERE task_id = ? AND client_interval_start IS NOT NULL"
            " AND client_interval_start + client_interval_duration < ? LIMIT ?)",
            (task_id.data, cutoff.seconds, limit),
        ).rowcount


class Datastore:
    """Connection manager + transaction runner (reference datastore.rs:107).

    SQLite engine. Engine-specific seams (overridden by
    PostgresDatastore): `DIALECT`, `_connect`, `_begin`,
    `_retryable_errors`, `_adapt`."""

    MAX_RETRIES = 16
    DIALECT = "sqlite"
    # WARN when one run_tx (including retries) exceeds this many
    # seconds. Configurable: database.slow_tx_warn_secs in the YAML
    # (binary_utils applies it) or the JANUS_SLOW_TX_WARN_S env var;
    # <= 0 disables.
    slow_tx_warn_s = float(os.environ.get("JANUS_SLOW_TX_WARN_S", "1.0"))
    # cap on one run_tx retry sleep; the actual sleep is full-jitter
    # uniform in [0, min(cap, base * 2^attempt)] so a retry storm after
    # an outage doesn't re-land every worker on the same instant.
    # Configurable via database.retry_max_interval_secs (binary_utils).
    retry_max_interval_s = 0.128
    retry_base_interval_s = 0.002

    def __init__(self, path: str, crypter: Crypter, clock):
        self._path = path
        self._crypter = crypter
        self._clock = clock
        self._local = threading.local()
        # every live per-thread connection, so close() / SIGTERM drain
        # can close them all instead of leaking every non-calling
        # thread's socket (the thread-local alone only reaches one)
        self._conn_registry: set = set()
        self._conn_registry_lock = threading.Lock()
        # scope suffix for the datastore.connect failpoint
        # (hit as `datastore.connect` + `datastore.connect.<scope>`), so
        # a schedule can take down ONE datastore in a multi-store
        # process (the chaos harness names the leader's "leader")
        self.failpoint_scope = os.path.basename(str(path)) or str(path)
        # attached by start_supervision(); run_tx feeds it success /
        # connection-failure observations even before the probe thread
        # exists
        self.supervisor: DatastoreSupervisor | None = None
        self._bootstrap_schema()

    def _bootstrap_schema(self) -> None:
        conn = self._connect()
        with conn:
            conn.executescript(_SCHEMA)
            row = conn.execute("SELECT version FROM schema_version").fetchone()
            if row is None:
                conn.execute("INSERT INTO schema_version (version) VALUES (?)", (SCHEMA_VERSION,))
            elif row[0] != SCHEMA_VERSION:
                # reference: supported_schema_versions! check (datastore.rs:103)
                raise RuntimeError(f"unsupported schema version {row[0]}")

    @property
    def clock(self):
        return self._clock

    @property
    def crypter(self) -> Crypter:
        """The at-rest crypter (shared with the upload spill journal so
        journaled shares stay encrypted on disk under the same keys)."""
        return self._crypter

    def _hit_connect_failpoint(self) -> None:
        """`datastore.connect` failpoint (error/delay/timeout): fires on
        EVERY connection checkout, cached or fresh, so an armed outage
        schedule models 'the database is unreachable' — not merely 'new
        dials fail while cached sockets keep working'. The error action
        raises this engine's connection-lost error type, which run_tx
        classifies as connection-class (discard + supervisor signal)."""
        from .. import failpoints

        failpoints.hit_scoped(
            "datastore.connect",
            self.failpoint_scope,
            error_factory=lambda: self._connection_lost_error(
                "injected connect failure (failpoint datastore.connect)"
            ),
            timeout_factory=lambda: self._connection_lost_error(
                "injected connect timeout (failpoint datastore.connect)"
            ),
        )

    def _connection_lost_error(self, msg: str) -> Exception:
        """This engine's connection-lost exception type (classified as
        kind="connection" by classify_error)."""
        return sqlite3.OperationalError(msg)

    def _register_conn(self, conn) -> None:
        with self._conn_registry_lock:
            self._conn_registry.add(conn)

    def _connect(self) -> sqlite3.Connection:
        self._hit_connect_failpoint()
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False: each thread still uses only its
            # own connection (threading.local discipline), but close()
            # and _discard() may run from another thread (test teardown,
            # SIGTERM drain) and must be able to close it
            conn = sqlite3.connect(
                self._path,
                timeout=30.0,
                uri=self._path.startswith("file:"),
                check_same_thread=False,
            )
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            conn.execute("PRAGMA foreign_keys=ON")
            self._local.conn = conn
            self._register_conn(conn)
        return conn

    def _begin(self, conn) -> None:
        conn.execute("BEGIN IMMEDIATE")

    def _adapt(self, conn):
        """Wrap the raw connection for Transaction's execute surface."""
        return conn

    def _discard(self, conn) -> None:
        """Drop a known-dead cached connection: close it, unregister it,
        and clear the thread-local so the next _connect dials fresh."""
        try:
            conn.close()
        except Exception:
            pass
        with self._conn_registry_lock:
            self._conn_registry.discard(conn)
        if getattr(self._local, "conn", None) is conn:
            self._local.conn = None

    def _discard_if_broken(self, conn) -> None:
        """Drop the cached connection if the engine marks it broken
        (engine hook; SQLite connections carry no broken flag)."""

    def classify_error(self, e: BaseException) -> str:
        """Typed datastore error classifier:

          "serialization"  contention — safe to retry on the SAME
                           connection (SQLITE_BUSY, injected TxConflict)
          "connection"     the connection (or the database under it) is
                           gone — discard the cached connection,
                           reconnect, and tell the supervisor
          "fatal"          schema/SQL error or a deterministic lease
                           conflict — retrying cannot help
          "other"          anything else
        """
        if isinstance(e, LeaseConflict):
            # deterministic: the lease is gone; a retry re-reads the
            # same mismatch 16 times and then raises anyway
            return "fatal"
        if isinstance(e, TxConflict):
            return "serialization"
        if isinstance(e, sqlite3.OperationalError):
            msg = str(e).lower()
            if "locked" in msg or "busy" in msg:
                return "serialization"
            if "no such" in msg or "syntax error" in msg:
                return "fatal"
            # "unable to open database file", "disk I/O error",
            # injected connect failures, ...
            return "connection"
        return "other"

    @property
    def _retryable_errors(self) -> tuple:
        return (sqlite3.OperationalError, TxConflict)

    def _tx_obj(self, conn) -> Transaction:
        return Transaction(self._adapt(conn), self._crypter, self._clock, dialect=self.DIALECT)

    def tx(self):
        """Single-attempt transaction as a context manager (no retry):
        commits on clean exit, rolls back on exception. For callers that
        want deterministic failures to surface immediately (tests,
        probes); production paths use run_tx."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            conn = self._connect()
            self._begin(conn)
            try:
                yield self._tx_obj(conn)
                conn.commit()
            except BaseException:
                conn.rollback()
                raise

        return cm()

    def _retry_sleep_s(self, attempt: int) -> float:
        """Full-jitter exponential backoff, capped at
        retry_max_interval_s: uniform in [0, min(cap, base * 2^n)] so
        concurrent workers retrying out of the same failure don't
        re-collide, and an operator can stretch the cap for outage-heavy
        deployments (database.retry_max_interval_secs)."""
        import random

        ceiling = min(
            max(0.0, float(self.retry_max_interval_s)),
            self.retry_base_interval_s * (1 << min(attempt, 30)),
        )
        return random.uniform(0.0, ceiling)

    def probe(self) -> None:
        """One cheap connectivity check on this thread's connection
        (the supervisor's health probe). Raises the engine error on
        failure, discarding the dead connection first so the next call
        dials fresh."""
        conn = None
        try:
            conn = self._connect()
            conn.execute("SELECT 1").fetchone()
            # leave no transaction open behind the probe (psycopg's
            # implicit BEGIN opens one at the first statement)
            conn.rollback()
        except BaseException:
            if conn is not None:
                self._discard(conn)
            raise

    def start_supervision(self, **kwargs) -> "DatastoreSupervisor":
        """Create, attach and start the background health supervisor
        (idempotent). kwargs go to DatastoreSupervisor."""
        if self.supervisor is None:
            self.supervisor = DatastoreSupervisor(self, **kwargs)
            self.supervisor.start()
        return self.supervisor

    def run_tx(self, fn, name: str = "tx"):
        """Run fn(Transaction) with retry on busy/conflict
        (reference run_tx_with_name, datastore.rs:216-242).

        Fault-injection seams (janus_tpu.failpoints, scoped by tx name
        so a schedule can target one transaction): `datastore.tx_begin`
        right after BEGIN, `datastore.commit` immediately before the
        commit (a crash here is the classic mid-commit death: work done,
        nothing durable), and `datastore.post_commit` after the commit
        but before the result reaches the caller (a crash here models
        dying after the DB committed but before anyone was acked — the
        retry/idempotency story the chaos harness proves). The error
        action raises TxConflict, i.e. a retryable conflict: run_tx's
        own retry loop must absorb injected commit failures the same
        way it absorbs real serialization failures."""
        from .. import failpoints, metrics

        def _inj() -> TxConflict:
            return TxConflict(f"injected conflict (failpoint, tx={name})")

        start = _time.monotonic()
        # supervisor accounting is per run_tx CALL, not per attempt: a
        # single doomed transaction retrying 3 times in ~10ms must not
        # masquerade as 3 independent outage observations and trip
        # down_threshold from a sub-second blip
        supervisor_notified = False
        for attempt in range(self.MAX_RETRIES):
            conn = None
            try:
                # inside the try: a failed (re)connect is a retryable
                # connection-class failure, not an immediate crash out
                conn = self._connect()
                self._begin(conn)
                failpoints.hit_scoped("datastore.tx_begin", name, error_factory=_inj)
                tx = self._tx_obj(conn)
                result = fn(tx)
                failpoints.hit_scoped("datastore.commit", name, error_factory=_inj)
                conn.commit()
                failpoints.hit_scoped("datastore.post_commit", name, error_factory=_inj)
                elapsed = _time.monotonic() - start
                metrics.tx_duration.observe(elapsed, tx=name)
                if 0 < self.slow_tx_warn_s < elapsed:
                    _log.warning(
                        "slow datastore transaction %s: %.3fs over %d attempt(s)"
                        " (threshold %.2fs)",
                        name, elapsed, attempt + 1, self.slow_tx_warn_s,
                    )
                if self.supervisor is not None:
                    self.supervisor.record_success()
                return result
            except self._retryable_errors as e:
                kind = self.classify_error(e)
                if kind != "fatal":
                    # fatal errors raise below without a retry; counting
                    # them here would invent an undocumented label value
                    metrics.tx_retries_total.add(tx=name, kind=kind)
                if conn is not None:
                    if kind == "connection":
                        # the connection (or the server) is gone: never
                        # retry INTO a dead cached connection — discard
                        # unconditionally so the next attempt redials
                        try:
                            conn.rollback()
                        except Exception:
                            pass
                        self._discard(conn)
                    else:
                        # contention: rollback best-effort, let the
                        # engine decide whether the connection survives
                        try:
                            conn.rollback()
                        except Exception:
                            self._discard(conn)
                        else:
                            self._discard_if_broken(conn)
                if (
                    kind == "connection"
                    and self.supervisor is not None
                    and not supervisor_notified
                ):
                    supervisor_notified = True
                    self.supervisor.record_failure(e)
                if kind == "fatal" or attempt == self.MAX_RETRIES - 1:
                    raise
                _time.sleep(self._retry_sleep_s(attempt))
            except BaseException:
                if conn is not None:
                    try:
                        conn.rollback()
                    except Exception:
                        self._discard(conn)
                raise

    def close(self) -> None:
        """Close EVERY per-thread connection (not just the calling
        thread's): handler/flusher/sampler threads each cached one, and
        test teardown or SIGTERM drain must not leak their sockets to
        the server."""
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        with self._conn_registry_lock:
            conns, self._conn_registry = list(self._conn_registry), set()
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass
        self._local.conn = None


class DatastoreSupervisor:
    """Per-process datastore connection supervisor: a background health
    probe drives a four-state machine

        up ──(connection failures / slow commits)──▶ degraded
        degraded ──(failures ≥ down_threshold)─────▶ down
        down ──(probe succeeds)────────────────────▶ recovering
        recovering ──(recover_threshold successes)─▶ up
                   └─(any failure)─────────────────▶ down

    fed by BOTH the probe and real transactions (run_tx reports every
    connection-class failure and every commit). Consumers:

      - ReportWriteBatcher: state != up ⇒ spill uploads to the journal
        instead of stalling handler threads on a dead database;
      - the admission controller: state != up ⇒ shed aggregate-step
        routes early (uploads keep flowing into the journal);
      - both job drivers: state == down ⇒ stop acquiring and step back
        with the reconnect cooldown instead of burning lease attempts;
      - /readyz: state == down ⇒ not ready (liveness /healthz stays up).

    While down, the probe retries on a full-jitter backoff growing from
    probe_interval_s to reconnect_max_interval_s. Exported as
    janus_datastore_up / janus_datastore_consecutive_failures and a
    `datastore` /statusz section."""

    STATES = ("up", "degraded", "down", "recovering")

    def __init__(
        self,
        ds: Datastore,
        probe_interval_s: float = 5.0,
        down_threshold: int = 3,
        recover_threshold: int = 2,
        reconnect_max_interval_s: float = 30.0,
        degraded_hold_s: float = 10.0,
    ):
        self._ds = ds
        self.probe_interval_s = max(0.05, float(probe_interval_s))
        self.down_threshold = max(1, int(down_threshold))
        self.recover_threshold = max(1, int(recover_threshold))
        self.reconnect_max_interval_s = max(
            self.probe_interval_s, float(reconnect_max_interval_s)
        )
        self.degraded_hold_s = max(0.0, float(degraded_hold_s))
        self._lock = threading.Lock()
        self._state = "up"
        self._consecutive_failures = 0
        self._recover_successes = 0
        self._down_since: float | None = None
        self._degraded_until = 0.0
        self._last_error: str | None = None
        self._transitions: dict[str, int] = {}
        self._stop = threading.Event()
        # set on every state change so the probe loop re-probes now
        # instead of sleeping out a full reconnect backoff (recovery
        # observed by real traffic should not wait ~30s for the probe)
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None
        self._publish_locked()

    # ------------------------------------------------------------------
    # state machine (callable from run_tx, the writer, and the probe)
    # ------------------------------------------------------------------
    def _set_state_locked(self, new: str) -> None:
        if new == self._state:
            return
        _log.warning("datastore supervisor: %s -> %s", self._state, new)
        self._state = new
        self._transitions[new] = self._transitions.get(new, 0) + 1
        self._down_since = _time.monotonic() if new == "down" else None
        # a state change is a probe-relevant event: wake the probe loop
        # so recovery isn't gated on a full backoff sleep
        self._kick.set()

    def _publish_locked(self) -> None:
        from .. import metrics

        metrics.datastore_up.set(0.0 if self._state == "down" else 1.0)
        metrics.datastore_consecutive_failures.set(float(self._consecutive_failures))

    def record_failure(self, error: BaseException | None = None) -> None:
        """One connection-class failure (probe or real transaction)."""
        with self._lock:
            self._consecutive_failures += 1
            self._recover_successes = 0
            if error is not None:
                self._last_error = f"{type(error).__name__}: {error}"
            if self._consecutive_failures >= self.down_threshold:
                self._set_state_locked("down")
            elif self._state == "up":
                self._set_state_locked("degraded")
            elif self._state == "recovering":
                self._set_state_locked("down")
            self._publish_locked()

    def record_success(self) -> None:
        """One successful commit or probe."""
        with self._lock:
            self._consecutive_failures = 0
            if self._state == "down":
                self._recover_successes = 1
                self._set_state_locked("recovering")
            elif self._state == "recovering":
                self._recover_successes += 1
                if self._recover_successes >= self.recover_threshold:
                    self._set_state_locked("up")
            elif self._state == "degraded" and _time.monotonic() >= self._degraded_until:
                self._set_state_locked("up")
            self._publish_locked()

    def record_slow_commit(self, elapsed_s: float) -> None:
        """A commit that exceeded the writer's spill latency threshold:
        the database is up but drowning — degrade (spilling uploads to
        the journal) for at least degraded_hold_s."""
        with self._lock:
            self._degraded_until = _time.monotonic() + self.degraded_hold_s
            if self._state == "up":
                self._set_state_locked("degraded")
            self._last_error = f"slow commit: {elapsed_s:.3f}s"
            self._publish_locked()

    # ------------------------------------------------------------------
    # consumers
    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def is_up(self) -> bool:
        return self.state == "up"

    def reconnect_delay_s(self) -> float:
        """How long a consumer (job driver step-back, Retry-After)
        should wait before trying the datastore again."""
        with self._lock:
            if self._state != "down" or self._down_since is None:
                return self.probe_interval_s
            downtime = _time.monotonic() - self._down_since
            return min(max(self.probe_interval_s, downtime / 2), self.reconnect_max_interval_s)

    def readiness(self) -> str | None:
        """None when ready; a human-readable reason when not (only a
        hard DOWN fails readiness — degraded still serves)."""
        with self._lock:
            if self._state == "down":
                return (
                    f"datastore down ({self._consecutive_failures} consecutive"
                    f" failures; last: {self._last_error})"
                )
            return None

    def status(self) -> dict:
        """/statusz section."""
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "down_for_s": (
                    round(_time.monotonic() - self._down_since, 1)
                    if self._down_since is not None
                    else None
                ),
                "last_error": self._last_error,
                "transitions": dict(self._transitions),
                "probe_interval_s": self.probe_interval_s,
            }

    # ------------------------------------------------------------------
    # probe loop
    # ------------------------------------------------------------------
    def _probe_once(self) -> None:
        try:
            self._ds.probe()
        except Exception as e:
            kind = self._ds.classify_error(e)
            if kind in ("connection", "other", "fatal"):
                self.record_failure(e)
            # serialization-class probe failures are contention, not an
            # outage: ignore (real traffic is getting through)
        else:
            self.record_success()

    def _probe_delay_s(self) -> float:
        import random

        if self.state != "down":
            return self.probe_interval_s
        # jittered reconnect backoff while down: grow toward the cap so
        # a long outage isn't hammered, full jitter so a fleet of
        # workers doesn't reconnect in lockstep
        with self._lock:
            downtime = (
                _time.monotonic() - self._down_since if self._down_since else 0.0
            )
        ceiling = min(
            self.reconnect_max_interval_s,
            self.probe_interval_s * (1 + downtime / (4 * self.probe_interval_s)),
        )
        return random.uniform(self.probe_interval_s * 0.5, ceiling)

    def _run(self) -> None:
        # first probe immediately: a process booted mid-outage must not
        # advertise up for a full interval
        while not self._stop.is_set():
            self._probe_once()
            self._kick.clear()
            # the sleep is cut short by a state change (_kick) so e.g.
            # a traffic-observed recovery while down re-probes at once
            self._kick.wait(self._probe_delay_s())
            if self._stop.is_set():
                return

    def start(self) -> "DatastoreSupervisor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="datastore-supervisor", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None


def _pg_schema() -> str:
    """The canonical DDL translated for Postgres: BLOB->BYTEA,
    INTEGER->BIGINT (sqlite INTEGER is 64-bit; pg INTEGER is 32 and
    timestamps/counters need 64)."""
    ddl = re.sub(r"\bBLOB\b", "BYTEA", _SCHEMA)
    ddl = re.sub(r"\bINTEGER\b", "BIGINT", ddl)
    return ddl


class PostgresDatastore(Datastore):
    """Postgres engine: the reference's horizontal-scaling deployment
    (datastore.rs:203-305) — REPEATABLE READ with retry on
    serialization failure, `FOR UPDATE SKIP LOCKED` lease claims
    (datastore.rs:1836-1905), many worker hosts against one database.

    `dsn` is a postgres:// / postgresql:// URL (psycopg format). An
    optional `schema` confines all tables to a named schema (used by
    the ephemeral test fixture for isolation). `driver` injects a
    psycopg-shaped module object at the exact seam this class touches
    (connect/IsolationLevel/errors/OperationalError) — production uses
    the real psycopg; in-image tests use
    janus_tpu.datastore.pg_fake.FakePostgresDriver so the adapter's
    SQL and retry/lease state machine have executable coverage without
    a server."""

    DIALECT = "postgres"

    def __init__(
        self,
        dsn: str,
        crypter: Crypter,
        clock,
        schema: str | None = None,
        driver=None,
    ):
        self._driver = driver if driver is not None else _psycopg
        if self._driver is None:
            raise RuntimeError(
                "database.url is postgres:// but psycopg is not installed"
            )
        self._dsn = dsn
        self._schema = schema
        super().__init__(dsn, crypter, clock)

    # arbitrary fixed key serializing concurrent schema bootstrap
    _BOOTSTRAP_LOCK_KEY = 0x6A616E7573  # "janus"

    def _bootstrap_schema(self) -> None:
        conn = self._connect()
        try:
            # advisory lock: multiple worker hosts booting against an
            # empty database would otherwise race the unguarded CREATEs
            # (pg_type_typname_nsp_index duplicate-key race) and the
            # schema_version check-then-insert
            conn.execute(
                "SELECT pg_advisory_xact_lock(%s)", (self._BOOTSTRAP_LOCK_KEY,)
            )
            if self._schema is not None:
                conn.execute(f'CREATE SCHEMA IF NOT EXISTS "{self._schema}"')
            for stmt in _pg_schema().split(";"):
                if stmt.strip():
                    conn.execute(stmt)
            cur = conn.execute("SELECT version FROM schema_version")
            row = cur.fetchone()
            if row is None:
                conn.execute(
                    "INSERT INTO schema_version (version) VALUES (%s)", (SCHEMA_VERSION,)
                )
            elif row[0] != SCHEMA_VERSION:
                raise RuntimeError(f"unsupported schema version {row[0]}")
            conn.commit()
        except BaseException:
            conn.rollback()
            raise

    def _connect(self):
        self._hit_connect_failpoint()
        conn = getattr(self._local, "conn", None)
        if conn is None:
            kwargs = {}
            if self._schema is not None:
                kwargs["options"] = f"-c search_path={self._schema}"
            conn = self._driver.connect(self._dsn, autocommit=False, **kwargs)
            conn.isolation_level = self._driver.IsolationLevel.REPEATABLE_READ
            self._local.conn = conn
            self._register_conn(conn)
        return conn

    def _begin(self, conn) -> None:
        # psycopg opens the transaction implicitly at the first statement
        # (autocommit=False) at the connection's isolation level
        pass

    def _adapt(self, conn):
        return _PgConnAdapter(conn)

    def _connection_lost_error(self, msg: str) -> Exception:
        return self._driver.OperationalError(msg)

    def _discard_if_broken(self, conn) -> None:
        if getattr(conn, "closed", False) or getattr(conn, "broken", False):
            self._discard(conn)

    def classify_error(self, e: BaseException) -> str:
        errs = self._driver.errors
        if isinstance(e, LeaseConflict):
            return "fatal"  # deterministic token mismatch — see sqlite engine
        if isinstance(
            e, (errs.SerializationFailure, errs.DeadlockDetected, TxConflict)
        ):
            return "serialization"
        if isinstance(e, self._driver.OperationalError):
            # psycopg raises OperationalError for lost/refused
            # connections and server shutdown ("server closed the
            # connection unexpectedly", admin shutdown, ...)
            return "connection"
        if isinstance(e, getattr(self._driver, "ProgrammingError", ())):
            return "fatal"
        return "other"

    @property
    def _retryable_errors(self) -> tuple:
        return (
            self._driver.errors.SerializationFailure,
            self._driver.errors.DeadlockDetected,
            self._driver.OperationalError,
            TxConflict,
        )

    def drop_schema(self) -> None:
        """Test teardown: drop the confined schema and everything in it."""
        assert self._schema is not None
        conn = self._connect()
        conn.execute(f'DROP SCHEMA IF EXISTS "{self._schema}" CASCADE')
        conn.commit()


def open_datastore(url: str, crypter: Crypter, clock):
    """database.url dispatch: postgres:// -> PostgresDatastore, anything
    else is a SQLite path (reference DbConfig, config.rs:61)."""
    if url.startswith(("postgres://", "postgresql://")):
        return PostgresDatastore(url, crypter, clock)
    return Datastore(url, crypter, clock)


class EphemeralDatastore:
    """Per-test datastore (the analog of the reference's ephemeral
    postgres testcontainer, datastore/test_util.rs:26-120).

    engine="sqlite" (default) uses a temp file. engine="postgres" uses
    the server at $JANUS_TEST_DATABASE_URL with a random per-fixture
    schema (dropped on cleanup) — the test parameterization skips it
    when psycopg or the URL is absent."""

    def __init__(self, clock=None, crypter: Crypter | None = None, engine: str = "sqlite"):
        from ..core.time_util import MockClock

        self.clock = clock if clock is not None else MockClock()
        self.crypter = crypter or Crypter()
        self._dir = None
        self._pg_driver = None
        if engine == "postgres":
            url = os.environ.get("JANUS_TEST_DATABASE_URL")
            if not url:
                raise RuntimeError("JANUS_TEST_DATABASE_URL not set")
            schema = "janus_test_" + secrets.token_hex(8)
            self.datastore = PostgresDatastore(url, self.crypter, self.clock, schema=schema)
        elif engine == "pgfake":
            # PostgresDatastore through the recorded-conversation fake
            # driver (pg_fake.py): PG adapter code paths, SQLite rows
            from .pg_fake import FakePostgresDriver

            self._pg_driver = FakePostgresDriver()
            self.datastore = PostgresDatastore(
                "postgresql://pgfake/janus",
                self.crypter,
                self.clock,
                schema="janus_pgfake",
                driver=self._pg_driver,
            )
        else:
            self._dir = tempfile.TemporaryDirectory(prefix="janus-tpu-ds-")
            self.datastore = Datastore(
                os.path.join(self._dir.name, "ds.sqlite"), self.crypter, self.clock
            )

    def cleanup(self) -> None:
        if isinstance(self.datastore, PostgresDatastore):
            self.datastore.drop_schema()
        self.datastore.close()
        if self._pg_driver is not None:
            self._pg_driver.cleanup()
        if self._dir is not None:
            self._dir.cleanup()
