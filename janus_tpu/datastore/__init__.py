"""Durable protocol state store.

Equivalent of reference aggregator_core/src/datastore.rs (SURVEY.md
section 2.4): a transactional facade with typed operations over the
DAP schema (tasks, client reports, aggregation jobs + leases, report
aggregations, sharded batch aggregations, collection jobs, aggregate
share jobs, batches, outstanding batches, global HPKE keys), with
AES-GCM encryption-at-rest for secret columns (`Crypter`,
datastore.rs:4889) and lease-based work queues
(acquire_incomplete_*_jobs, datastore.rs:1836).

Backend is SQLite here (no Postgres driver ships in this image); the
SQL and the op surface are kept Postgres-shaped — `FOR UPDATE SKIP
LOCKED` becomes a single-statement UPDATE..RETURNING claim, REPEATABLE
READ + serialization-retry becomes BEGIN IMMEDIATE + busy-retry — so a
server-Postgres backend is a drop-in (SURVEY.md section 7 step 4). All
protocol state is durable, so any worker resumes any job mid-step
(checkpoint/resume, SURVEY.md section 5).
"""

from .models import (
    AcquiredAggregationJob,
    AcquiredCollectionJob,
    AggregateShareJob,
    AggregationJobModel,
    AggregationJobState,
    Batch,
    BatchAggregation,
    BatchAggregationState,
    BatchState,
    CollectionJobModel,
    CollectionJobState,
    LeaderStoredReport,
    Lease,
    OutstandingBatch,
    ReportAggregationModel,
    ReportAggregationState,
)
from .store import (
    Crypter,
    Datastore,
    EphemeralDatastore,
    PostgresDatastore,
    open_datastore,
)

__all__ = [n for n in dir() if not n.startswith("_")]
