"""Datastore row models.

Equivalent of reference aggregator_core/src/datastore/models.rs
(LeaderStoredReport:78, AggregationJob:220, Lease:434,
ReportAggregation:586 + state:714, BatchAggregation:843 + state:1042,
CollectionJob:1055 + state:1182, AggregateShareJob:1287,
OutstandingBatch:1412, Batch:1473).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, replace

from ..messages import (
    AggregationJobId,
    BatchId,
    CollectionJobId,
    Duration,
    HpkeCiphertext,
    Interval,
    PrepareError,
    ReportId,
    ReportIdChecksum,
    TaskId,
    Time,
)


class AggregationJobState(str, enum.Enum):
    """reference models.rs:374."""

    IN_PROGRESS = "in_progress"
    FINISHED = "finished"
    ABANDONED = "abandoned"
    DELETED = "deleted"


class ReportAggregationState(str, enum.Enum):
    """reference models.rs:714: Start / WaitingLeader(transition) /
    WaitingHelper(prep state) / Finished / Failed(error)."""

    START = "start"
    WAITING_LEADER = "waiting_leader"
    WAITING_HELPER = "waiting_helper"
    FINISHED = "finished"
    FAILED = "failed"


class BatchAggregationState(str, enum.Enum):
    """reference models.rs:1042."""

    AGGREGATING = "aggregating"
    COLLECTED = "collected"


class CollectionJobState(str, enum.Enum):
    """reference models.rs:1182."""

    START = "start"
    COLLECTABLE = "collectable"
    FINISHED = "finished"
    DELETED = "deleted"
    ABANDONED = "abandoned"


class BatchState(str, enum.Enum):
    """reference models.rs:1456."""

    OPEN = "open"
    CLOSING = "closing"
    CLOSED = "closed"


@dataclass(frozen=True)
class LeaderStoredReport:
    """A decrypted report at rest on the leader (reference models.rs:78)."""

    task_id: TaskId
    report_id: ReportId
    client_time: Time
    public_share: bytes
    leader_input_share: bytes  # decoded leader share, encrypted at rest
    helper_encrypted_input_share: HpkeCiphertext


@dataclass(frozen=True)
class AggregationJobModel:
    """reference models.rs:220."""

    task_id: TaskId
    job_id: AggregationJobId
    aggregation_parameter: bytes
    partial_batch_identifier: bytes  # encoded PartialBatchSelector body ('' for time-interval)
    client_timestamp_interval: Interval
    state: AggregationJobState
    step: int
    last_request_hash: bytes | None = None
    # W3C traceparent persisted by whoever created the job (the leader's
    # job creator / the helper's init handler); both job drivers adopt it
    # so a step's spans join the creating trace across processes and
    # driver restarts (janus_tpu.trace.use_traceparent)
    trace_context: str | None = None

    def with_state(self, state: AggregationJobState) -> "AggregationJobModel":
        return replace(self, state=state)

    def with_step(self, step: int) -> "AggregationJobModel":
        return replace(self, step=step)

    def with_last_request_hash(self, h: bytes) -> "AggregationJobModel":
        return replace(self, last_request_hash=h)


@dataclass(frozen=True)
class ShardSpec:
    """Fleet shard predicate for the batched lease claims
    (docs/ARCHITECTURE.md "Running a fleet"): a replica owns the jobs
    whose persisted shard_key lands on its (shard_index mod
    shard_count); jobs OUTSIDE the shard become claimable only after
    they have sat eligible for steal_after_s — so a dead replica's
    shard drains instead of starving, while live replicas never
    contend on each other's rows."""

    shard_count: int = 1
    shard_index: int = 0
    steal_after_s: int = 30

    @property
    def active(self) -> bool:
        return self.shard_count > 1


@dataclass(frozen=True)
class Lease:
    """An acquired job lease (reference models.rs:434)."""

    token: bytes
    expiry: Time
    attempts: int


@dataclass(frozen=True)
class AcquiredAggregationJob:
    """reference models.rs:494. shard_key is the row's STORED shard
    hash at claim time (None from legacy constructors; < 0 = the
    affinity was released by a clean hand-back) — the steal classifier
    reads it so a rolling restart's hand-backs never count as
    steals."""

    task_id: TaskId
    job_id: AggregationJobId
    lease: Lease
    shard_key: int | None = None


@dataclass(frozen=True)
class AcquiredCollectionJob:
    """reference models.rs:540 (shard_key: see AcquiredAggregationJob)."""

    task_id: TaskId
    collection_job_id: CollectionJobId
    lease: Lease
    shard_key: int | None = None


@dataclass(frozen=True)
class ReportAggregationModel:
    """reference models.rs:586.

    prep_blob holds the serialized per-report prepare payload for the
    waiting states: the leader's transition (out share + verifier
    context) or the helper's prepare state; opaque at this layer and
    encrypted at rest.
    """

    task_id: TaskId
    job_id: AggregationJobId
    report_id: ReportId
    client_time: Time
    ord: int
    state: ReportAggregationState
    prep_blob: bytes = b""
    prepare_error: PrepareError | None = None

    def finished(self) -> "ReportAggregationModel":
        return replace(self, state=ReportAggregationState.FINISHED, prep_blob=b"")

    def failed(self, err: PrepareError) -> "ReportAggregationModel":
        return replace(
            self, state=ReportAggregationState.FAILED, prep_blob=b"", prepare_error=err
        )


@dataclass(frozen=True)
class BatchAggregation:
    """One shard of a batch's running aggregate (reference models.rs:843).

    Sharding exists to spread row contention (the reference picks a
    random shard 0..shard_count at accumulate time, accumulator.rs:92).
    """

    task_id: TaskId
    batch_identifier: bytes  # encoded Interval or BatchId
    aggregation_parameter: bytes
    ord: int
    state: BatchAggregationState
    aggregate_share: bytes | None  # encoded field vector, None for empty shard
    report_count: int
    client_timestamp_interval: Interval
    checksum: ReportIdChecksum

    def merged_with(self, other: "BatchAggregation") -> "BatchAggregation":
        """Merge another shard-update into this one (same key)."""
        assert self.ord == other.ord and self.batch_identifier == other.batch_identifier
        raise NotImplementedError("merge happens in the aggregator layer with field math")


@dataclass(frozen=True)
class CollectionJobModel:
    """reference models.rs:1055."""

    task_id: TaskId
    collection_job_id: CollectionJobId
    query: bytes  # encoded Query
    aggregation_parameter: bytes
    batch_identifier: bytes
    state: CollectionJobState
    report_count: int | None = None
    client_timestamp_interval: Interval | None = None
    leader_aggregate_share: bytes | None = None  # encrypted at rest
    helper_encrypted_aggregate_share: bytes | None = None
    # W3C traceparent persisted by the collection-create handler; the
    # collection job driver adopts it (see AggregationJobModel)
    trace_context: str | None = None


@dataclass(frozen=True)
class AggregateShareJob:
    """Helper-side record of a served aggregate share (reference models.rs:1287)."""

    task_id: TaskId
    batch_identifier: bytes
    aggregation_parameter: bytes
    helper_aggregate_share: bytes  # encoded field vector, encrypted at rest
    report_count: int
    checksum: ReportIdChecksum


@dataclass(frozen=True)
class Batch:
    """reference models.rs:1473."""

    task_id: TaskId
    batch_identifier: bytes
    aggregation_parameter: bytes
    state: BatchState
    outstanding_aggregation_jobs: int
    client_timestamp_interval: Interval


@dataclass(frozen=True)
class OutstandingBatch:
    """A fixed-size batch being filled (reference models.rs:1412)."""

    task_id: TaskId
    batch_id: BatchId
    time_bucket_start: Time | None
    size: int = 0  # reports assigned so far (incl. in-flight)
