"""janus_tpu: a TPU-native DAP-07 aggregator framework.

A ground-up re-design of the capabilities of Janus (the Rust DAP aggregator,
see /root/reference) for TPU hardware: the per-report VDAF hot path
(Prio3 FLP prove/query/decide + output-share accumulation, which the
reference runs serially per report on CPU via the external `prio` crate,
cf. reference aggregator/src/aggregator/aggregation_job_driver.rs:329-402)
becomes batched field arithmetic over `[batch, ...]` uint64 arrays in
JAX/XLA, with Pallas kernels for the hottest ops.

Layering (mirrors SURVEY.md section 1):
  fields/    -- Field64 / Field128 modular arithmetic (limb tricks on u64 lanes)
  vdaf/      -- XOF, NTT, FLP, Prio3, ping-pong topology  (L0)
  messages/  -- DAP-07 TLS-syntax wire structs            (L1)
  core/      -- HPKE, clocks, retries, auth, registry     (L2)
  datastore/ -- transactional store, lease queue, crypter (L3)
  aggregator/-- protocol handlers + job drivers           (L4/L5)
  client.py, collector.py                                 (L6)

64-bit integer support is required throughout (field elements live in
uint64 lanes; XLA lowers them to 32-bit pairs on TPU), so importing this
package enables jax_enable_x64.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
